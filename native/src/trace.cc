#include "tpubc/trace.h"

#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <functional>
#include <random>
#include <thread>

#include "tpubc/runtime.h"

namespace tpubc {

namespace {

// Wall base captured once per process; spans advance it with steady_clock
// deltas so in-process durations are monotonic while cross-process
// timestamps still line up on one Chrome-trace timeline.
struct TimeBase {
  int64_t wall_us;
  std::chrono::steady_clock::time_point steady;
  TimeBase()
      : wall_us(std::chrono::duration_cast<std::chrono::microseconds>(
                    std::chrono::system_clock::now().time_since_epoch())
                    .count()),
        steady(std::chrono::steady_clock::now()) {}
};

const TimeBase& time_base() {
  static TimeBase base;
  return base;
}

int64_t steady_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - time_base().steady)
      .count();
}

std::string random_hex64() {
  // Thread-local generator: id creation sits on the reconcile/admission
  // hot paths, so no shared lock; seeded per-thread from random_device.
  thread_local std::mt19937_64 rng(
      std::random_device{}() ^
      (std::hash<std::thread::id>{}(std::this_thread::get_id()) << 1));
  uint64_t v = rng();
  char buf[17];
  std::snprintf(buf, sizeof(buf), "%016llx", static_cast<unsigned long long>(v));
  return buf;
}

thread_local Span* g_current_span = nullptr;

// Chrome trace tids must be integers; derive a stable one from the trace
// id so a request's spans share one row even when recorded from several
// threads.
int64_t chrome_tid(const std::string& trace_id) {
  if (trace_id.empty()) return 0;
  return static_cast<int64_t>(std::hash<std::string>{}(trace_id) & 0x7fffffff);
}

}  // namespace

std::string new_trace_id() { return random_hex64(); }
std::string new_span_id() { return random_hex64(); }

int64_t trace_now_us() { return time_base().wall_us + steady_us(); }

Tracer::Tracer() : capacity_(kDefaultCapacity) {
  if (const char* env = std::getenv("TPUBC_TRACE_BUFFER")) {
    char* end = nullptr;
    long v = std::strtol(env, &end, 10);
    if (end && *end == '\0' && v > 0) capacity_ = static_cast<size_t>(v);
  }
  ring_.resize(capacity_);
}

Tracer& Tracer::instance() {
  static Tracer t;
  return t;
}

void Tracer::set_process_name(const std::string& name) {
  std::lock_guard<std::mutex> lock(mutex_);
  process_ = name;
}

void Tracer::record(TraceSpan span) {
  std::lock_guard<std::mutex> lock(mutex_);
  if (count_ == capacity_) ++dropped_;  // cursor slot held the oldest span
  ring_[next_] = std::move(span);
  next_ = (next_ + 1) % capacity_;
  if (count_ < capacity_) ++count_;
}

Json Tracer::to_json() const {
  std::lock_guard<std::mutex> lock(mutex_);
  Json spans = Json::array();
  // Oldest-first: start at the cursor when the ring has wrapped.
  size_t start = count_ == capacity_ ? next_ : 0;
  for (size_t i = 0; i < count_; ++i) {
    const TraceSpan& s = ring_[(start + i) % capacity_];
    Json attrs = Json::object();
    for (const auto& kv : s.attrs) attrs.set(kv.first, kv.second);
    spans.push_back(Json::object({
        {"trace_id", s.trace_id},
        {"span_id", s.span_id},
        {"parent_id", s.parent_id},
        {"name", s.name},
        {"start_us", s.start_us},
        {"dur_us", s.dur_us},
        {"attrs", std::move(attrs)},
    }));
  }
  return Json::object({
      {"process", process_},
      {"dropped", static_cast<int64_t>(dropped_)},
      {"spans", std::move(spans)},
  });
}

Json Tracer::to_chrome() const {
  std::lock_guard<std::mutex> lock(mutex_);
  const int64_t pid = static_cast<int64_t>(getpid());
  Json events = Json::array();
  events.push_back(Json::object({
      {"name", "process_name"},
      {"ph", "M"},
      {"pid", pid},
      {"tid", 0},
      {"args", Json::object({{"name", process_}})},
  }));
  size_t start = count_ == capacity_ ? next_ : 0;
  for (size_t i = 0; i < count_; ++i) {
    const TraceSpan& s = ring_[(start + i) % capacity_];
    Json args = Json::object({
        {"trace_id", s.trace_id},
        {"span_id", s.span_id},
        {"parent_id", s.parent_id},
    });
    for (const auto& kv : s.attrs) args.set(kv.first, kv.second);
    events.push_back(Json::object({
        {"name", s.name},
        {"cat", process_},
        {"ph", "X"},
        {"ts", s.start_us},
        {"dur", s.dur_us},
        {"pid", pid},
        // One Chrome row per trace keeps a request's spans visually
        // nested even though they were recorded from several threads.
        {"tid", chrome_tid(s.trace_id)},
        {"args", std::move(args)},
    }));
  }
  return Json::object({{"traceEvents", std::move(events)},
                       {"displayTimeUnit", "ms"}});
}

void Tracer::reset() {
  std::lock_guard<std::mutex> lock(mutex_);
  next_ = count_ = dropped_ = 0;
}

bool Tracer::dump_to_env_file() const {
  const char* path = std::getenv("TPUBC_TRACE_FILE");
  if (!path || !*path) return false;
  std::string body = to_chrome().dump();
  std::FILE* f = std::fopen(path, "w");
  if (!f) return false;
  size_t n = std::fwrite(body.data(), 1, body.size(), f);
  std::fclose(f);
  return n == body.size();
}

Span::Span(std::string name) { init(std::move(name), "", ""); }

Span::Span(std::string name, std::string trace_id, std::string parent_id) {
  init(std::move(name), std::move(trace_id), std::move(parent_id));
}

void Span::init(std::string name, std::string trace_id, std::string parent_id) {
  span_.name = std::move(name);
  span_.span_id = new_span_id();
  if (!trace_id.empty()) {
    span_.trace_id = std::move(trace_id);
    span_.parent_id = std::move(parent_id);
  } else if (g_current_span) {
    span_.trace_id = g_current_span->trace_id();
    span_.parent_id = g_current_span->span_id();
  } else {
    span_.trace_id = new_trace_id();
  }
  start_steady_us_ = steady_us();
  span_.start_us = time_base().wall_us + start_steady_us_;
  prev_ = g_current_span;
  g_current_span = this;
}

Span::~Span() {
  span_.dur_us = steady_us() - start_steady_us_;
  g_current_span = prev_;
  Tracer::instance().record(std::move(span_));
  Metrics::instance().inc("trace_spans_total");
}

void Span::attr(const std::string& key, const std::string& value) {
  span_.attrs.emplace_back(key, value);
}

void Span::attr(const std::string& key, int64_t value) {
  span_.attrs.emplace_back(key, std::to_string(value));
}

Span* current_span() { return g_current_span; }

}  // namespace tpubc
