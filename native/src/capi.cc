// extern "C" surface exposing the pure cores to Python (ctypes).
//
// This is how the pytest suite exercises the real native logic — the same
// object code the daemons link — without a cluster. Every function takes
// UTF-8 JSON/string arguments and returns a malloc'd UTF-8 string the
// caller must release with tpubc_free. Exceptions are converted to
// {"error": "..."} payloads.
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>

#include "tpubc/admission_core.h"
#include "tpubc/crd.h"
#include "tpubc/google_auth.h"
#include "tpubc/json.h"
#include "tpubc/log.h"
#include "tpubc/reconcile_core.h"
#include "tpubc/runtime.h"
#include "tpubc/sheet_core.h"
#include "tpubc/statusz.h"
#include "tpubc/topology.h"
#include "tpubc/trace.h"
#include "tpubc/util.h"
#include "tpubc/yaml.h"

namespace {

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

template <typename Fn>
char* guarded(Fn&& fn) {
  try {
    return dup_string(fn());
  } catch (const std::exception& e) {
    return dup_string(tpubc::Json::object({{"error", std::string(e.what())}}).dump());
  }
}

}  // namespace

extern "C" {

void tpubc_free(char* p) { std::free(p); }

char* tpubc_version() { return dup_string("tpu-bootstrap-controller 0.1.0"); }

char* tpubc_crd_yaml() {
  return guarded([] { return tpubc::crd_yaml(); });
}

char* tpubc_crd_json() {
  return guarded([] { return tpubc::crd_definition().dump(); });
}

char* tpubc_to_yaml(const char* json) {
  return guarded([&] { return tpubc::to_yaml(tpubc::Json::parse(json)); });
}

char* tpubc_json_roundtrip(const char* text) {
  return guarded([&] { return tpubc::Json::parse(text).dump(); });
}

char* tpubc_json_patch(const char* doc, const char* patch) {
  return guarded([&] {
    tpubc::Json d = tpubc::Json::parse(doc);
    d.apply_patch(tpubc::Json::parse(patch));
    return d.dump();
  });
}

char* tpubc_validate_topology(const char* accelerator, const char* topology) {
  return guarded([&] {
    tpubc::TopologyError err = tpubc::validate_topology(accelerator, topology);
    return tpubc::Json::object({{"ok", err.ok}, {"reason", err.reason}}).dump();
  });
}

char* tpubc_slice_geometry(const char* accelerator, const char* topology) {
  return guarded([&] { return tpubc::slice_geometry(accelerator, topology).to_json().dump(); });
}

char* tpubc_default_topology(const char* accelerator) {
  return guarded([&] { return tpubc::default_topology(accelerator); });
}

char* tpubc_classify_username(const char* username, const char* prefix) {
  return guarded([&] {
    tpubc::Username u = tpubc::classify_username(username, prefix);
    return tpubc::Json::object(
               {{"original", u.original}, {"kube", u.kube}, {"is_admin", u.is_admin}})
        .dump();
  });
}

char* tpubc_default_admission_config() {
  return guarded([] { return tpubc::default_admission_config().dump(); });
}

char* tpubc_mutate(const char* request, const char* config) {
  return guarded(
      [&] { return tpubc::mutate(tpubc::Json::parse(request), tpubc::Json::parse(config)).dump(); });
}

char* tpubc_mutate_review(const char* review, const char* config) {
  return guarded([&] {
    return tpubc::mutate_review(tpubc::Json::parse(review), tpubc::Json::parse(config)).dump();
  });
}

char* tpubc_default_controller_config() {
  return guarded([] { return tpubc::default_controller_config().dump(); });
}

char* tpubc_desired_children(const char* ub, const char* config) {
  return guarded([&] {
    tpubc::Json out = tpubc::Json::array();
    for (auto& child :
         tpubc::desired_children(tpubc::Json::parse(ub), tpubc::Json::parse(config)))
      out.push_back(std::move(child));
    return out.dump();
  });
}

char* tpubc_build_jobset(const char* ub, const char* config) {
  return guarded([&] {
    return tpubc::build_jobset(tpubc::Json::parse(ub), tpubc::Json::parse(config)).dump();
  });
}

char* tpubc_slice_status(const char* ub, const char* jobset) {
  return guarded([&] {
    return tpubc::slice_status(tpubc::Json::parse(ub), tpubc::Json::parse(jobset)).dump();
  });
}

char* tpubc_jobset_spec_changed(const char* ub, const char* desired_jobset) {
  return guarded([&] {
    return tpubc::Json(tpubc::jobset_spec_changed(tpubc::Json::parse(ub),
                                                  tpubc::Json::parse(desired_jobset)))
        .dump();
  });
}

char* tpubc_slice_event(const char* ub, const char* old_phase, const char* new_slice,
                        const char* timestamp) {
  return guarded([&] {
    return tpubc::slice_event(tpubc::Json::parse(ub), old_phase,
                              tpubc::Json::parse(new_slice), timestamp)
        .dump();
  });
}

char* tpubc_refresh_event(const char* prev, const char* fresh) {
  return guarded([&] {
    return tpubc::refresh_event(tpubc::Json::parse(prev), tpubc::Json::parse(fresh)).dump();
  });
}

char* tpubc_infer_header(const char* header) {
  return guarded([&] { return tpubc::infer_header(header); });
}

char* tpubc_parse_sheet(const char* csv) {
  return guarded([&] { return tpubc::parse_sheet(csv).dump(); });
}

char* tpubc_default_synchronizer_config() {
  return guarded([] { return tpubc::default_synchronizer_config().dump(); });
}

char* tpubc_build_quota(const char* row, const char* device) {
  return guarded([&] { return tpubc::build_quota(tpubc::Json::parse(row), device).dump(); });
}

char* tpubc_plan_sync(const char* ub_list, const char* rows, const char* config) {
  return guarded([&] {
    return tpubc::plan_sync(tpubc::Json::parse(ub_list), tpubc::Json::parse(rows),
                            tpubc::Json::parse(config))
        .dump();
  });
}

char* tpubc_node_pool_capacity(const char* nodes, const char* device) {
  return guarded([&] {
    return std::to_string(tpubc::node_pool_capacity(tpubc::Json::parse(nodes), device));
  });
}

char* tpubc_base64url_encode(const char* data) {
  return guarded([&] { return tpubc::base64url_encode(data); });
}

char* tpubc_service_account_jwt(const char* sa_key_json, const char* scope, const char* iat) {
  return guarded([&] {
    return tpubc::build_service_account_jwt(tpubc::Json::parse(sa_key_json), scope,
                                            std::stoll(iat));
  });
}

char* tpubc_sha256_hex(const char* data) {
  return guarded([&] { return tpubc::sha256_hex(data); });
}

char* tpubc_base64_encode(const char* data) {
  return guarded([&] { return tpubc::base64_encode(data); });
}

// ---- telemetry read-back (tracing / metrics / log filtering) --------------
// The pytest suite drives the SAME tracer/metrics instances the cores
// above record into: call tpubc_mutate_review, read the span back here.

char* tpubc_trace_dump() {
  return guarded([] { return tpubc::Tracer::instance().to_json().dump(); });
}

char* tpubc_trace_chrome() {
  return guarded([] { return tpubc::Tracer::instance().to_chrome().dump(); });
}

char* tpubc_trace_reset() {
  return guarded([] {
    tpubc::Tracer::instance().reset();
    return std::string("{}");
  });
}

// Record one complete span (test fixture: exercises ring-buffer bounds
// and parent links without touching a policy core).
char* tpubc_trace_test_span(const char* name, const char* trace_id, const char* parent_id) {
  return guarded([&] {
    tpubc::Span s(name, trace_id, parent_id);
    return tpubc::Json::object({{"trace_id", s.trace_id()}, {"span_id", s.span_id()}}).dump();
  });
}

char* tpubc_metrics_inc(const char* name, const char* delta) {
  return guarded([&] {
    tpubc::Metrics::instance().inc(name, std::stoll(delta));
    return std::string("{}");
  });
}

char* tpubc_metrics_observe(const char* name, const char* value) {
  return guarded([&] {
    tpubc::Metrics::instance().observe(name, std::stod(value));
    return std::string("{}");
  });
}

char* tpubc_metrics_quantile(const char* name, const char* q) {
  return guarded([&] {
    return tpubc::Json(tpubc::Metrics::instance().quantile(name, std::stod(q))).dump();
  });
}

char* tpubc_metrics_json() {
  return guarded([] { return tpubc::Metrics::instance().to_json().dump(); });
}

char* tpubc_metrics_prometheus() {
  return guarded([] { return tpubc::Metrics::instance().to_prometheus(); });
}

char* tpubc_metrics_reset() {
  return guarded([] {
    tpubc::Metrics::instance().reset();
    return std::string("{}");
  });
}

// Effective level for a target under a TPUBC_LOG directive spec
// ("info,kube=debug") — the pure core of the env filter.
char* tpubc_log_level_for(const char* spec, const char* target) {
  return guarded([&] { return tpubc::log_level_for(spec, target); });
}

// Warning-flood token bucket, driven with an EXPLICIT clock so tests pin
// refill behavior deterministically (the daemons feed monotonic_ms).
char* tpubc_log_ratelimit_allow(const char* target, const char* message,
                                const char* now_ms) {
  return guarded([&] {
    return tpubc::Json(
               tpubc::log_ratelimit_allow(target, message, std::stoll(now_ms)))
        .dump();
  });
}

char* tpubc_log_ratelimit_reset() {
  return guarded([] {
    tpubc::log_ratelimit_reset();
    return std::string("{}");
  });
}

// ---- statusz flight recorder ----------------------------------------------
// The pytest suite drives the SAME recorder instance the daemons write:
// ring bounds, error capture, and trace-id join are tested here without a
// cluster.

char* tpubc_statusz_record(const char* object, const char* entry_json) {
  return guarded([&] {
    tpubc::Json e = tpubc::Json::parse(entry_json);
    tpubc::StatuszEntry entry;
    entry.ts_ms = e.get_int("ts_ms", 0);
    entry.op = e.get_string("op");
    entry.duration_ms = e.get("duration_ms").is_number()
                            ? e.get("duration_ms").as_double()
                            : 0.0;
    entry.error = e.get_string("error");
    entry.trace_id = e.get_string("trace_id");
    entry.detail = e.get_string("detail");
    tpubc::Statusz::instance().record(object, std::move(entry));
    return std::string("{}");
  });
}

char* tpubc_statusz_set_state(const char* key, const char* value_json) {
  return guarded([&] {
    tpubc::Statusz::instance().set_state(key, tpubc::Json::parse(value_json));
    return std::string("{}");
  });
}

char* tpubc_statusz_json(const char* object_filter) {
  return guarded(
      [&] { return tpubc::Statusz::instance().to_json(object_filter).dump(); });
}

char* tpubc_statusz_reset() {
  return guarded([] {
    tpubc::Statusz::instance().reset();
    return std::string("{}");
  });
}

char* tpubc_workload_summary(const char* metrics, const char* scraped_at) {
  return guarded([&] {
    return tpubc::workload_summary(tpubc::Json::parse(metrics), scraped_at)
        .dump();
  });
}

char* tpubc_base64_decode(const char* data) {
  return guarded([&] { return tpubc::base64_decode(data); });
}

}  // extern "C"
