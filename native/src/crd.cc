#include "tpubc/crd.h"

#include "tpubc/topology.h"
#include "tpubc/yaml.h"

namespace tpubc {

namespace {

Json string_schema(const std::string& description) {
  return Json::object({{"description", description}, {"type", "string"}});
}

Json nullable_string_schema(const std::string& description) {
  return Json::object({{"description", description}, {"nullable", true}, {"type", "string"}});
}

Json int_schema(const std::string& description) {
  return Json::object({{"description", description}, {"format", "int64"}, {"type", "integer"}});
}

// k8s Quantity: string or integer ("4", "16Gi", 4).
Json quantity_schema() {
  return Json::object({
      {"x-kubernetes-int-or-string", true},
      {"anyOf", Json::array({Json::object({{"type", "integer"}}), Json::object({{"type", "string"}})})},
  });
}

// Vendored subset of io.k8s.api.core.v1.ResourceQuotaSpec — mirrors the
// schema the reference embeds via k8s-openapi (crd.yaml:23-96 in the
// reference chart) without re-deriving it from upstream at build time.
Json quota_schema() {
  Json scope_selector = Json::object({
      {"description", "scopeSelector is also a collection of filters like scopes that must match "
                      "each object tracked by a quota but expressed using ScopeSelectorOperator "
                      "in combination with possible values."},
      {"nullable", true},
      {"type", "object"},
      {"properties",
       Json::object({
           {"matchExpressions",
            Json::object({
                {"description", "A list of scope selector requirements by scope of the resources."},
                {"type", "array"},
                {"items",
                 Json::object({
                     {"type", "object"},
                     {"required", Json::array({Json("operator"), Json("scopeName")})},
                     {"properties",
                      Json::object({
                          {"operator", string_schema("Represents a scope's relationship to a set of values.")},
                          {"scopeName", string_schema("The name of the scope that the selector applies to.")},
                          {"values",
                           Json::object({{"description", "An array of string values."},
                                         {"type", "array"},
                                         {"items", Json::object({{"type", "string"}})}})},
                      })},
                 })},
            })},
       })},
  });
  return Json::object({
      {"description", "ResourceQuota for the user namespace. Hard caps include TPU chip "
                      "requests (requests.google.com/tpu)."},
      {"nullable", true},
      {"type", "object"},
      {"properties",
       Json::object({
           {"hard", Json::object({{"description",
                                   "hard is the set of desired hard limits for each named resource."},
                                  {"type", "object"},
                                  {"additionalProperties", quantity_schema()}})},
           {"scopeSelector", scope_selector},
           {"scopes",
            Json::object({{"description",
                           "A collection of filters that must match each object tracked by a quota."},
                          {"type", "array"},
                          {"items", Json::object({{"type", "string"}})}})},
       })},
  });
}

// Vendored subset of io.k8s.api.rbac.v1.Role (metadata-free; the controller
// stamps metadata — /root/reference/src/controller.rs:113-124 pattern).
Json role_schema() {
  Json policy_rule = Json::object({
      {"type", "object"},
      {"properties",
       Json::object({
           {"apiGroups",
            Json::object({{"type", "array"}, {"items", Json::object({{"type", "string"}})}})},
           {"nonResourceURLs",
            Json::object({{"type", "array"}, {"items", Json::object({{"type", "string"}})}})},
           {"resourceNames",
            Json::object({{"type", "array"}, {"items", Json::object({{"type", "string"}})}})},
           {"resources",
            Json::object({{"type", "array"}, {"items", Json::object({{"type", "string"}})}})},
           {"verbs",
            Json::object({{"type", "array"}, {"items", Json::object({{"type", "string"}})}})},
       })},
      {"required", Json::array({Json("verbs")})},
  });
  return Json::object({
      {"description", "Role created in the user namespace. Optional; if not specified, no "
                      "additional Role is created."},
      {"nullable", true},
      {"type", "object"},
      {"x-kubernetes-preserve-unknown-fields", true},
      {"properties",
       Json::object({
           {"rules", Json::object({{"description", "Rules holds all the PolicyRules for this Role"},
                                   {"type", "array"},
                                   {"items", policy_rule}})},
       })},
  });
}

Json rolebinding_schema() {
  return Json::object({
      {"description", "RoleBinding (metadata-less) for the user namespace. If not specified, "
                      "the admission webhook defaults it to the configured ClusterRole bound "
                      "to the requesting user."},
      {"nullable", true},
      {"type", "object"},
      {"required", Json::array({Json("role_ref")})},
      {"properties",
       Json::object({
           {"role_ref",
            Json::object({
                {"type", "object"},
                {"required", Json::array({Json("api_group"), Json("kind"), Json("name")})},
                {"properties", Json::object({
                                   {"api_group", string_schema("APIGroup of the referenced role.")},
                                   {"kind", string_schema("Kind of the referenced role.")},
                                   {"name", string_schema("Name of the referenced role.")},
                               })},
            })},
           {"subjects",
            Json::object({
                {"nullable", true},
                {"type", "array"},
                {"items",
                 Json::object({
                     {"type", "object"},
                     {"required", Json::array({Json("kind"), Json("name")})},
                     {"properties",
                      Json::object({
                          {"api_group", nullable_string_schema("APIGroup of the subject.")},
                          {"kind", string_schema("Kind of the subject (User/Group/ServiceAccount).")},
                          {"name", string_schema("Name of the subject.")},
                          {"namespace", nullable_string_schema("Namespace of the subject.")},
                      })},
                 })},
            })},
       })},
  });
}

Json tpu_schema() {
  Json accel_enum = Json::array();
  for (const auto& name : known_accelerators()) accel_enum.push_back(name);
  return Json::object({
      {"description",
       "TPU slice request. When present, the controller materializes a gang-scheduled "
       "multi-host JobSet targeting one ICI-connected slice: nodeSelectors "
       "cloud.google.com/gke-tpu-accelerator + cloud.google.com/gke-tpu-topology and "
       "per-host google.com/tpu chip requests."},
      {"nullable", true},
      {"type", "object"},
      {"properties",
       Json::object({
           {"accelerator", Json::object({{"description",
                                          "GKE TPU accelerator type (gke-tpu-accelerator node "
                                          "selector value)."},
                                         {"type", "string"},
                                         {"enum", accel_enum}})},
           {"topology", nullable_string_schema(
                            "Slice topology, e.g. \"2x2\" (v5e single host) or \"4x4x4\" "
                            "(64-chip v5p). Defaulted by the admission webhook when omitted.")},
           {"slices", Json::object({{"description",
                                     "Multislice: number of ICI-connected slices of this "
                                     "topology, data-parallel over DCN (default 1). Each "
                                     "slice is one replica of the JobSet's replicated job."},
                                    {"nullable", true},
                                    {"format", "int64"},
                                    {"type", "integer"}})},
           {"image", nullable_string_schema("Container image for slice workers.")},
           {"command",
            Json::object({{"description", "Worker entrypoint override."},
                          {"nullable", true},
                          {"type", "array"},
                          {"items", Json::object({{"type", "string"}})}})},
           {"args", Json::object({{"description", "Worker args."},
                                  {"nullable", true},
                                  {"type", "array"},
                                  {"items", Json::object({{"type", "string"}})}})},
           {"chips", Json::object({{"description", "Total chips in the slice (computed by the "
                                                   "admission webhook from topology)."},
                                   {"nullable", true},
                                   {"format", "int64"},
                                   {"type", "integer"}})},
           {"hosts", Json::object({{"description", "Worker hosts in the slice (computed)."},
                                   {"nullable", true},
                                   {"format", "int64"},
                                   {"type", "integer"}})},
           {"chips_per_host", Json::object({{"description", "google.com/tpu request per host "
                                                            "(computed)."},
                                            {"nullable", true},
                                            {"format", "int64"},
                                            {"type", "integer"}})},
           {"max_restarts", Json::object({{"description", "JobSet failurePolicy.maxRestarts for "
                                                          "the slice (gang restart budget)."},
                                          {"nullable", true},
                                          {"format", "int64"},
                                          {"type", "integer"}})},
           {"ttl_seconds_after_finished",
            Json::object({{"description",
                           "JobSet ttlSecondsAfterFinished: a finished "
                           "(Succeeded/Failed) slice is garbage-collected "
                           "after this many seconds, releasing its quota'd "
                           "chips without operator action. Absent = keep. "
                           "Floor 60: a shorter TTL races the controller's "
                           "observation of the finished slice (the terminal "
                           "phase would never be recorded and the slice "
                           "would re-run forever)."},
                          {"nullable", true},
                          {"format", "int64"},
                          {"type", "integer"},
                          {"minimum", 60}})},
           {"env", Json::object({{"description",
                                  "Extra environment for slice workers — the workload "
                                  "config surface (WORKLOAD_MESH, WORKLOAD_SCHEDULE, "
                                  "WORKLOAD_STEPS, ...). Names starting with TPUBC_ or "
                                  "MEGASCALE_, and JOB_COMPLETION_INDEX, are reserved "
                                  "for the slice bootstrap contract and rejected by "
                                  "admission."},
                                 {"nullable", true},
                                 {"type", "object"},
                                 {"additionalProperties",
                                  Json::object({{"type", "string"}})}})},
       })},
  });
}

Json gpu_schema() {
  return Json::object({
      {"description",
       "GPU request (reference parity path). Mutually exclusive with spec.tpu. The "
       "admission webhook defaults count and injects requests.nvidia.com/gpu (+ "
       "requests.nvidia.com/mig-1g.10gb) quota — the reference's key set "
       "(synchronizer.rs:268-278) — when spec.quota is absent."},
      {"nullable", true},
      {"type", "object"},
      {"properties",
       Json::object({
           {"count", Json::object({{"description", "nvidia.com/gpu devices requested "
                                                   "(defaulted to 1 by the webhook)."},
                                   {"nullable", true},
                                   {"format", "int64"},
                                   {"type", "integer"}})},
           {"mig_count", Json::object({{"description", "nvidia.com/mig-1g.10gb slices "
                                                       "requested."},
                                       {"nullable", true},
                                       {"format", "int64"},
                                       {"type", "integer"}})},
       })},
  });
}

Json status_schema() {
  return Json::object({
      {"nullable", true},
      {"type", "object"},
      {"properties",
       Json::object({
           {"synchronized_with_sheet",
            Json::object({{"description",
                           "Set true by the synchronizer once an authorized sheet row has been "
                           "applied; gates RoleBinding and JobSet creation."},
                          {"type", "boolean"},
                          // Defaulted, NOT required (diverges from the
                          // reference's required bool deliberately): this
                          // build's status has TWO writers — the controller
                          // merge-patches status.slice.phase before the
                          // synchronizer ever syncs a new CR, and a
                          // required sibling would 422 that first write
                          // against a real apiserver (caught by the fake
                          // apiserver's write-path schema validation).
                          {"default", false}})},
           {"slice",
            Json::object({
                {"description", "Observed state of the TPU slice JobSet."},
                {"nullable", true},
                {"type", "object"},
                {"properties",
                 Json::object({
                     {"phase",
                      nullable_string_schema(
                          "Pending | Provisioning | Running | Succeeded | Failed | Absent.")},
                     {"chips", int_schema("Chips granted.")},
                     {"hosts", int_schema("Hosts granted.")},
                     {"slices", int_schema("ICI slices granted (multislice).")},
                     {"observed_generation",
                      int_schema("spec generation this observation belongs "
                                 "to (the observedGeneration idiom): scopes "
                                 "terminal-phase stickiness and the TTL "
                                 "one-shot gate to the spec that produced "
                                 "the outcome.")},
                     {"jobset", nullable_string_schema("Name of the materialized JobSet.")},
                     {"spec_hash",
                      nullable_string_schema(
                          "spec-hash label of the observed JobSet: which "
                          "JobSet spec this observation belongs to. The "
                          "controller compares it against the desired "
                          "JobSet's hash to decide delete-then-recreate "
                          "(JobSet pod templates are immutable).")},
                     {"workload",
                      Json::object({
                          {"description",
                           "Workload health summary scraped from worker "
                           "0's /metrics.json (opt-in via "
                           "CONF_WORKLOAD_SCRAPE on the controller): is "
                           "the slice training/serving and at what rate, "
                           "without port-forwarding to the pod."},
                          {"nullable", true},
                          {"type", "object"},
                          {"properties",
                           Json::object({
                               {"last_step",
                                int_schema("Last completed train step.")},
                               {"tokens_per_sec",
                                Json::object({{"description",
                                               "Recent training (or serving) "
                                               "token throughput."},
                                              {"type", "number"}})},
                               {"serve_qps",
                                Json::object({{"description",
                                               "Recent serving completions "
                                               "per second."},
                                              {"type", "number"}})},
                               {"last_scrape",
                                nullable_string_schema(
                                    "RFC3339 timestamp of the scrape this "
                                    "summary came from.")},
                           })},
                      })},
                     {"conditions",
                      Json::object({
                          {"description", "Slice-provisioning conditions "
                                          "(SliceProvisioned, WorkersReady)."},
                          {"nullable", true},
                          {"type", "array"},
                          {"items",
                           Json::object({
                               {"type", "object"},
                               {"required", Json::array({Json("type"), Json("status")})},
                               {"properties",
                                Json::object({
                                    {"type", Json::object({{"type", "string"}})},
                                    {"status", Json::object({{"type", "string"}})},
                                    {"reason", nullable_string_schema("Machine-readable cause.")},
                                })},
                           })},
                      })},
                 })},
            })},
       })},
  });
}

}  // namespace

Json crd_definition() {
  Json spec_props = Json::object({
      {"kube_username", nullable_string_schema("Kubernetes username")},
      {"quota", quota_schema()},
      {"role", role_schema()},
      {"rolebinding", rolebinding_schema()},
      {"tpu", tpu_schema()},
      {"gpu", gpu_schema()},
  });

  Json schema = Json::object({
      {"description", "Auto-generated derived type for UserBootstrapSpec via `CustomResource`"},
      {"type", "object"},
      {"required", Json::array({Json("spec")})},
      {"properties", Json::object({
                         {"spec", Json::object({{"type", "object"}, {"properties", spec_props}})},
                         {"status", status_schema()},
                     })},
  });

  return Json::object({
      {"apiVersion", "apiextensions.k8s.io/v1"},
      {"kind", "CustomResourceDefinition"},
      {"metadata", Json::object({{"name", std::string(kPlural) + "." + kGroup}})},
      {"spec",
       Json::object({
           {"group", kGroup},
           {"names", Json::object({
                         {"categories", Json::array()},
                         {"kind", kKind},
                         {"plural", kPlural},
                         {"shortNames", Json::array({Json(kShortName)})},
                         {"singular", kSingular},
                     })},
           {"scope", "Cluster"},
           {"versions",
            Json::array({Json::object({
                // `kubectl get tub` shows the lifecycle at a glance:
                // PHASE (the slice ladder), the requested hardware
                // (ACCELERATOR, CHIPS), the sheet gate (SYNCED), and AGE.
                {"additionalPrinterColumns",
                 Json::array({
                     Json::object({{"jsonPath", ".status.slice.phase"},
                                   {"name", "Phase"},
                                   {"type", "string"}}),
                     Json::object({{"jsonPath", ".spec.tpu.accelerator"},
                                   {"name", "Accelerator"},
                                   {"type", "string"}}),
                     Json::object({{"jsonPath", ".status.slice.chips"},
                                   {"name", "Chips"},
                                   {"type", "integer"}}),
                     Json::object({{"jsonPath", ".status.synchronized_with_sheet"},
                                   {"name", "Synced"},
                                   {"type", "boolean"}}),
                     Json::object({{"jsonPath", ".metadata.creationTimestamp"},
                                   {"name", "Age"},
                                   {"type", "date"}}),
                 })},
                {"name", kVersion},
                {"schema", Json::object({{"openAPIV3Schema", schema}})},
                {"served", true},
                {"storage", true},
                {"subresources", Json::object({{"status", Json::object()}})},
            })})},
       })},
  });
}

std::string crd_yaml() { return to_yaml(crd_definition()); }

}  // namespace tpubc
