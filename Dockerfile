# Build the four native daemons, ship them in one slim runtime image —
# the reference's single-image/three-daemons packaging model.
FROM debian:bookworm-slim AS build

# No libssl-dev on purpose: the build declares the stable libssl C ABI
# itself and links libssl.so.3 by soname (native/CMakeLists.txt).
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ cmake ninja-build libssl3 \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY native/ native/
RUN cmake -S native -B native/build -G Ninja -DCMAKE_BUILD_TYPE=Release \
    && ninja -C native/build

FROM debian:bookworm-slim AS runtime

RUN apt-get update && apt-get install -y --no-install-recommends \
    ca-certificates libssl3 \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY --from=build /src/native/build/tpubc-crdgen /app/
COPY --from=build /src/native/build/tpubc-controller /app/
COPY --from=build /src/native/build/tpubc-admission /app/
COPY --from=build /src/native/build/tpubc-synchronizer /app/
