{{/* Chart name */}}
{{- define "tpu-bootstrap.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{/* Fully qualified app name */}}
{{- define "tpu-bootstrap.fullname" -}}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- $name := default .Chart.Name .Values.nameOverride -}}
{{- if contains $name .Release.Name -}}
{{- .Release.Name | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- printf "%s-%s" .Release.Name $name | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- end -}}
{{- end -}}

{{/* Common labels */}}
{{- define "tpu-bootstrap.labels" -}}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version | replace "+" "_" | trunc 63 | trimSuffix "-" }}
app.kubernetes.io/name: {{ include "tpu-bootstrap.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{/* Selector labels for one component; expects dict with ctx + component */}}
{{- define "tpu-bootstrap.selectorLabels" -}}
app.kubernetes.io/name: {{ include "tpu-bootstrap.name" .ctx }}
app.kubernetes.io/instance: {{ .ctx.Release.Name }}
app.kubernetes.io/component: {{ .component }}
{{- end -}}

{{/* Component resource name */}}
{{- define "tpu-bootstrap.componentName" -}}
{{- printf "%s-%s" (include "tpu-bootstrap.fullname" .ctx) .component -}}
{{- end -}}
