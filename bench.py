#!/usr/bin/env python3
"""Control-plane benchmark for tpu-bootstrap-controller.

Metric (BASELINE.json): reconciles/sec + p50 CR-apply->slice latency. The
reference publishes no numbers and its Rust toolchain is unavailable, so
the baseline stand-in is this build's own controller constrained to the
reference's architecture: one serial reconcile worker (the kube-rs runtime
applies objects one CR at a time — reference controller.rs:50-155 performs
1-4 sequential API writes per pass on a single reconcile loop).

Protocol per configuration:
  1. start the fake API server (in-process) pre-loaded with N sheet-synced
     TPU CRs (v5e 2x2 slices — BASELINE config #3 shape);
  2. start tpubc-controller; t0 = first reconcile observed;
  3. wait until every CR's JobSet exists (full convergence); value =
     N / elapsed = CR convergences per second;
  4. with the controller warm, create K CRs one at a time and measure
     apply->JobSet-visible latency; report the p50.

Prints ONE JSON line:
  {"metric": "reconciles_per_sec", "value": ..., "unit": "reconciles/s",
   "vs_baseline": parallel/serial, ...extras}
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from tpu_bootstrap import nativelib  # noqa: E402
from tpu_bootstrap.fakeapi import FAKEAPI_VERSION, FakeKube  # noqa: E402

N_BURST = 200
K_LATENCY = 40

KEY_JS = lambda ns: ("apis/jobset.x-k8s.io/v1alpha2", ns, "jobsets")  # noqa: E731

SYNCED = {"synchronized_with_sheet": True}


def cr_spec():
    return {
        "kube_username": "u",
        "quota": {"hard": {"requests.google.com/tpu": "4"}},
        "rolebinding": {
            "role_ref": {
                "api_group": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "edit",
            }
        },
        "tpu": {"accelerator": "tpu-v5-lite-podslice", "topology": "2x2"},
    }


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_health(port, proc, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"controller exited: {proc.stderr.read().decode()[-2000:]}")
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1) as r:
                if r.read() == b"pong":
                    return
        except OSError:
            time.sleep(0.02)
    raise TimeoutError("controller health timeout")


def run_config(workers: int, n_burst: int = N_BURST, k_latency: int = K_LATENCY,
               latency_ms: float = 0):
    fake = FakeKube(latency_ms=latency_ms).start()
    port = free_port()
    try:
        for i in range(n_burst):
            fake.create_ub(f"bench-{i:04d}", spec=cr_spec(), status=dict(SYNCED))

        proc = subprocess.Popen(
            [str(REPO / "native" / "build" / "tpubc-controller")],
            env={
                **os.environ,
                "CONF_KUBE_API_URL": fake.url,
                "CONF_LISTEN_ADDR": "127.0.0.1",
                "CONF_LISTEN_PORT": str(port),
                "CONF_RECONCILE_WORKERS": str(workers),
                "TPUBC_LOG": "error",
            },
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        try:
            wait_health(port, proc)
            t0 = time.time()
            deadline = t0 + 300
            while time.time() < deadline:
                with fake.store.lock:
                    done = sum(
                        1
                        for i in range(n_burst)
                        if fake.store.objects.get(KEY_JS(f"bench-{i:04d}"), {}).get(
                            f"bench-{i:04d}-slice"
                        )
                    )
                if done == n_burst:
                    break
                time.sleep(0.005)
            else:
                raise TimeoutError("burst convergence timeout")
            burst_elapsed = time.time() - t0
            burst_rate = n_burst / burst_elapsed

            # p50 apply -> JobSet-visible latency on a warm controller.
            latencies = []
            for i in range(k_latency):
                name = f"lat-{i:04d}"
                t_apply = time.time()
                fake.create_ub(name, spec=cr_spec(), status=dict(SYNCED))
                while True:
                    with fake.store.lock:
                        if fake.store.objects.get(KEY_JS(name), {}).get(f"{name}-slice"):
                            break
                    if time.time() - t_apply > 30:
                        raise TimeoutError(f"latency CR {name} never converged")
                    time.sleep(0.001)
                latencies.append((time.time() - t_apply) * 1000)
            latencies.sort()
            p50 = latencies[len(latencies) // 2]
            # In-daemon reconcile-duration p50 from the daemon's own
            # histogram (the /metrics surface a real cluster would scrape).
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json", timeout=2
                ) as r:
                    daemon_p50 = json.loads(r.read()).get(
                        "tpubc_reconcile_duration_ms_p50", -1)
            except OSError:
                daemon_p50 = -1
            return burst_rate, burst_elapsed, p50, daemon_p50
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    finally:
        fake.stop()


# The workload bench body runs in its OWN subprocess: TPU backend init
# through the axon tunnel can be slow or hang outright (round 1 died with
# "Unable to initialize backend 'axon'"), and it must never take the
# control-plane metric down with it. Progressive-output protocol: the
# subprocess re-prints the full accumulated JSON object after every
# milestone; the parent keeps the LAST parseable line, so a later crash,
# OOM, or timeout only loses the sections that never ran — the numbers
# already measured survive (VERDICT r1 item 1: the TPU half of BENCH must
# not be a blank because one sub-bench died).
WORKLOAD_BENCH_SCRIPT = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["TPUBC_REPO"])
out = {}

def emit():
    print(json.dumps(out), flush=True)

import jax
import jax.numpy as jnp
from jax import lax

# The axon sitecustomize hook pins the platform regardless of env vars;
# only the config API overrides it. Honoring JAX_PLATFORMS here makes the
# non-TPU fast path actually fast (CI/dev hosts) while the bench host's
# JAX_PLATFORMS=axon pins the tunneled chip explicitly.
_plats = os.environ.get("JAX_PLATFORMS", "")
if _plats:
    jax.config.update("jax_platforms", _plats)

t_init = time.time()
backend = jax.default_backend()
dev = jax.devices()[0]
out["workload_backend"] = backend
out["workload_device"] = str(getattr(dev, "device_kind", dev.platform))
out["backend_init_s"] = round(time.time() - t_init, 1)
if backend not in ("tpu", "axon") and dev.platform != "tpu":
    out["workload_bench_error"] = f"not a TPU backend: {backend}/{dev.platform}"
    emit(); sys.exit(0)
# Prove the chip actually executes before sinking time into compiles.
float(jnp.sum(jnp.ones((128, 128), jnp.bfloat16) @ jnp.ones((128, 128), jnp.bfloat16)))
out["chip_alive"] = True
emit()

# Early Mosaic smoke for the decode-attention kernel (tiny shapes, fast
# compile): its first-ever hardware compile happens here rather than
# deep inside the int8-KV decode section, so a Mosaic rejection shows
# up as one labeled boolean instead of a lost section.
try:
    from tpu_bootstrap.workload.decode_attention import decode_attention_int8

    _q = jnp.ones((1, 4, 64), jnp.bfloat16)
    _kq = jnp.ones((1, 32, 2, 64), jnp.int8)
    _ks = jnp.ones((1, 32, 2), jnp.float32)
    float(jnp.sum(decode_attention_int8(
        _q, _kq, _ks, _kq, _ks, jnp.arange(32) < 20).astype(jnp.float32)))
    out["decode_kernel_mosaic_ok"] = True
except Exception as e:  # noqa: BLE001
    out["decode_kernel_mosaic_ok"] = False
    out["decode_kernel_mosaic_error"] = f"{type(e).__name__}: {e}"[:300]
emit()

# Same early smoke for the PAGED decode-attention kernel: its scalar-
# prefetched index maps are the one Mosaic feature the resident kernel
# never exercises, so a rejection must surface as this boolean, not as
# a lost serving section.
try:
    from tpu_bootstrap.workload.decode_attention import (
        paged_decode_attention_int8)

    _pkq = jnp.ones((5, 8, 2, 64), jnp.int8)
    _pks = jnp.ones((5, 8, 2), jnp.float32)
    _pbt = jnp.asarray([[3, 1], [2, 4]], jnp.int32)
    float(jnp.sum(paged_decode_attention_int8(
        jnp.ones((2, 4, 64), jnp.bfloat16), _pkq, _pks, _pkq, _pks,
        _pbt, jnp.asarray([12, 7], jnp.int32)).astype(jnp.float32)))
    out["paged_kernel_mosaic_ok"] = True
except Exception as e:  # noqa: BLE001
    out["paged_kernel_mosaic_ok"] = False
    out["paged_kernel_mosaic_error"] = f"{type(e).__name__}: {e}"[:300]
emit()

PEAK_BF16 = 197e12  # v5e chip peak, bf16

try:
    from tpu_bootstrap.workload.flash_attention import flash_attention
    from tpu_bootstrap.workload.ring_attention import reference_attention

    def timed(core, q, k, v, iters=10):
        # Loop on-device via scan: per-dispatch tunnel latency (ms-scale on
        # axon) would otherwise swamp the kernel time.
        @jax.jit
        def many(q, k, v):
            def body(qq, _):
                return core(qq, k, v).astype(jnp.bfloat16), ()
            out, _ = lax.scan(body, q, None, length=iters)
            return out

        float(jnp.sum(many(q, k, v).astype(jnp.float32)))  # compile+warm
        t0 = time.time()
        float(jnp.sum(many(q, k, v).astype(jnp.float32)))
        return (time.time() - t0) / iters * 1e3

    # argnums=(0,1,2): grads for q AND k/v — the default (argnums=0)
    # would let XLA DCE the dk/dv backward kernel entirely.
    def grad_sum(f):
        g = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32)),
                     argnums=(0, 1, 2))
        def combined(q, k, v):
            dq, dk, dv = g(q, k, v)
            return dq + dk + dv
        return combined

    g_flash = grad_sum(lambda q, k, v: flash_attention(q, k, v, interpret=False))
    g_dense = grad_sum(reference_attention)

    # Fixed 32k tokens per measurement (batch*seq), so the seq sweep shows
    # the O(seq^2)-HBM vs O(seq)-HBM scaling at equal work granularity.
    for batch, seq in ((4, 2048), (2, 4096), (1, 8192)):
        shape = (batch, seq, 8, 64)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)
        flash_ms = timed(g_flash, q, k, v)
        out[f"flash_attn_fwd_bwd_ms_seq{seq}"] = round(flash_ms, 3)
        emit()
        dense_ms = timed(g_dense, q, k, v)
        out[f"dense_attn_fwd_bwd_ms_seq{seq}"] = round(dense_ms, 3)
        key = "flash_attn_speedup" if seq == 2048 else f"flash_attn_speedup_seq{seq}"
        out[key] = round(dense_ms / flash_ms, 3)
        emit()
except Exception as e:  # noqa: BLE001
    out["flash_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Train-step throughput + MFU on the single chip: a ~134M-param LM (bf16
# activations, flash attention) — big enough that the MXU, not dispatch,
# dominates.
try:
    from tpu_bootstrap.workload.model import ModelConfig
    from tpu_bootstrap.workload.sharding import MeshConfig, batch_shardings, build_mesh
    from tpu_bootstrap.workload.train import TrainConfig, init_train_state, make_train_step

    cfg = TrainConfig(
        model=ModelConfig(vocab_size=32768, num_layers=8, num_heads=16, head_dim=64,
                          embed_dim=1024, mlp_dim=4096, max_seq_len=1024,
                          compute_dtype=jnp.bfloat16),
        mesh=MeshConfig(data=1, fsdp=1, seq=1, tensor=1),
        attention="flash",
    )
    mesh = build_mesh(cfg.mesh, jax.devices()[:1])
    params, opt_state, p_sh = init_train_state(cfg, mesh, jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh, p_sh)
    batch = 8
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.model.max_seq_len), 0,
                           cfg.model.vocab_size),
        batch_shardings(mesh))
    n_steps = 10

    # Async dispatch loop with ONE host sync at the end: the device
    # executes the steps back-to-back (donated buffers, no transfers), so
    # elapsed/n is honest per-step time; a host sync per step would add a
    # full tunnel round-trip each.
    params, opt_state, loss = step(params, opt_state, tokens)  # compile+warm
    float(loss)
    t0 = time.time()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)
    step_ms = (time.time() - t0) / n_steps * 1e3
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tokens_per_step = batch * (cfg.model.max_seq_len - 1)
    # 6ND matmul flops + 12*B*L*H*S^2*D attention flops, fwd+bwd.
    m = cfg.model
    attn_flops = 12 * batch * m.num_layers * m.num_heads * (m.max_seq_len - 1) ** 2 * m.head_dim
    flops_per_step = 6 * n_params * tokens_per_step + attn_flops
    out.update({
        "train_step_ms": round(step_ms, 3),
        "train_model_params_m": round(n_params / 1e6, 1),
        "train_tokens_per_sec": round(tokens_per_step / (step_ms / 1e3), 1),
        "train_mfu_pct": round(100 * flops_per_step / (step_ms / 1e3) / PEAK_BF16, 2),
    })
except Exception as e:  # noqa: BLE001
    out["train_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Quantization quality on a TRAINED model (VERDICT r4 weak #5): the
# int8/int4 quality ladder and the speculative-acceptance claim were
# only ever measured on random init — the worst case for argmax
# stability and silent about task degradation. Continue the
# already-compiled train step on the learnable noisy-permutation task
# (workload/quality.py) until the model predicts confidently, then
# measure what quantization actually does at task level. The chain runs
# over a 4096-token sub-vocabulary so ~300 steps of the 134M bench
# model see ~600 examples per bigram entry (full 32k vocab would need
# 8x the steps for the same coverage).
try:
    from tpu_bootstrap.workload.quality import (
        eval_quality, markov_batch, spec_acceptance)
    from tpu_bootstrap.workload.quant import (
        quantize_params as _qp, quantize_params4 as _qp4)

    CHAIN_V = 4096
    t0 = time.time()
    for i in range(300):
        qb = jax.device_put(
            jnp.asarray(markov_batch(i, batch, cfg.model.max_seq_len, CHAIN_V)),
            batch_shardings(mesh))
        params, opt_state, loss = step(params, opt_state, qb)
    out["quality_train_loss"] = round(float(loss), 3)
    out["quality_train_s"] = round(time.time() - t0, 1)

    def _bf16(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x, p)

    tbf = _bf16(params)
    held_out = jnp.asarray(markov_batch(10_000, batch, 129, CHAIN_V))
    q8 = eval_quality(tbf, _qp(params), cfg.model, held_out)
    out.update({
        "trained_int8_ppl_delta": q8["ppl_delta"],
        "trained_int8_argmax_agreement_pct": q8["argmax_agreement_pct"],
        "trained_ppl_base": q8["ppl_base"],
    })
    emit()
    q4 = eval_quality(tbf, _qp4(params), cfg.model, held_out)
    out.update({
        "trained_int4_ppl_delta": q4["ppl_delta"],
        "trained_int4_argmax_agreement_pct": q4["argmax_agreement_pct"],
    })
    emit()
    # Speculative acceptance with the int8 self-draft on the TRAINED
    # model — the number the "int8 rarely flips a trained argmax" claim
    # predicts should beat the random-init speculative_mean_committed
    # measured further down.
    sprompt = jnp.asarray(markov_batch(20_000, batch, 16, CHAIN_V))
    acc = spec_acceptance(tbf, _qp(params), cfg.model, sprompt,
                          steps=48, gamma=4)
    out["spec_accept_trained_mean_committed"] = acc["mean_committed"]
    emit()

    # Distilled 2-layer draft: the configuration where speculation
    # should WIN wall clock — the int8 SELF-draft pays a full-size model
    # stream per proposal (measured 0.22x below), while a 4x-shallower
    # distilled student drafts at ~1/4 the cost and, trained on the
    # same task, keeps acceptance high. Teacher rides as an EXPLICIT jit
    # arg (quality.distill_draft) — closing over 134M params would 413
    # the tunnel's compile endpoint.
    import dataclasses as _dc
    from tpu_bootstrap.workload.quality import distill_draft

    scfg = _dc.replace(cfg.model, num_layers=2)
    t0 = time.time()
    draft, dloss = distill_draft(
        params, cfg.model, scfg, steps=150,
        batch_fn=lambda i: markov_batch(500 + i, batch,
                                        cfg.model.max_seq_len, CHAIN_V))
    out.update({"distill_train_s": round(time.time() - t0, 1),
                "distill_loss": round(dloss, 3)})
    dbf = _bf16(draft)
    acc2 = spec_acceptance(tbf, dbf, cfg.model, sprompt, steps=48, gamma=4,
                           draft_cfg=scfg)
    out["spec_accept_distilled_mean_committed"] = acc2["mean_committed"]
    emit()

    # Wall clock on the trained target: plain greedy vs distilled-draft
    # speculative (two-point step measurement cancels prefill).
    from tpu_bootstrap.workload.decode import generate as _gen
    from tpu_bootstrap.workload.speculative import speculative_generate as _sg

    def t_plain(steps):
        t0 = time.time()
        int(_gen(tbf, sprompt, cfg.model, steps)[0, -1])
        return time.time() - t0

    def t_spec(steps):
        t0 = time.time()
        int(_sg(tbf, dbf, sprompt, cfg.model, scfg, steps, gamma=4)[0, -1])
        return time.time() - t0

    def stepsec(f):
        f(32), f(96)  # compile + warm both shapes
        samples = []
        for _ in range(3):
            a, b = f(32), f(96)
            samples.append(max((b - a) / 64, 1e-9))
        return sorted(samples)[1]

    ps, ss = stepsec(t_plain), stepsec(t_spec)
    out.update({
        "spec_distilled_tokens_per_sec": round(batch / ss, 1),
        "spec_distilled_speedup": round(ps / ss, 3),
    })
except Exception as e:  # noqa: BLE001
    out["quality_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Decode throughput: greedy generation with the KV cache (the serving
# path) — tokens/sec at batch 8 on the single chip. Same ~134M-param
# model as the train bench: decode is weight-bandwidth-bound, so the
# model must be big enough that weight bytes (not dispatch noise)
# dominate — also what makes the int8 comparison meaningful.
try:
    from tpu_bootstrap.workload.decode import generate
    from tpu_bootstrap.workload.model import ModelConfig, init_params

    dcfg = ModelConfig(vocab_size=32768, num_layers=8, num_heads=16, head_dim=64,
                       embed_dim=1024, mlp_dim=4096, max_seq_len=512,
                       compute_dtype=jnp.bfloat16)
    def to_bf16(params):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x, params)

    dmaster = init_params(dcfg, jax.random.PRNGKey(0))
    # The bf16 baseline stores weights in bf16 (f32 masters would double
    # the streamed bytes and flatter the int8 comparison); quantization
    # happens from the f32 masters.
    dparams = to_bf16(dmaster)
    dbatch, d1, d2 = 8, 64, 192
    dprompt = jax.random.randint(jax.random.PRNGKey(1), (dbatch, 64), 0, dcfg.vocab_size)

    def timed_gen(params, steps, cfg=dcfg, kv_quant=False):
        # int(...) readback is the sync: block_until_ready can return
        # before device completion on the tunneled backend. Callers warm
        # each (params, cfg, steps) once before sampling.
        t0 = time.time()
        int(generate(params, dprompt, cfg, steps, kv_quant=kv_quant)[0, -1])
        return time.time() - t0

    def decode_step_s(params, cfg=dcfg, kv_quant=False):
        # Two-point measurement: the d2-d1 step difference cancels the
        # prefill (and any fixed dispatch overhead), giving pure
        # per-decode-step cost. Median of 3 pairs: a single pair is noisy
        # through the tunnel (a delayed readback skews the subtraction in
        # either direction, so min would report optimistic outliers).
        timed_gen(params, d1, cfg, kv_quant), timed_gen(params, d2, cfg, kv_quant)
        samples = []
        for _ in range(3):
            t1 = timed_gen(params, d1, cfg, kv_quant)
            t2 = timed_gen(params, d2, cfg, kv_quant)
            samples.append(max((t2 - t1) / (d2 - d1), 1e-9))
        return sorted(samples)[len(samples) // 2]

    # Roofline accounting (VERDICT r3 item 6): a decode step streams every
    # weight byte once (the KV cache is negligible at this section's
    # L <= 256); bytes/token localizes the gap between the measured int8
    # speedup and its 2x weight-bandwidth ceiling. The bytes-a-step-
    # actually-streams accounting now lives in quant.decode_stream_bytes
    # (one definition, shared with the interpret-mode byte tests so the
    # claim regresses in tier-1 without a chip).
    PEAK_HBM = 819e9  # v5e HBM bandwidth, bytes/s

    from tpu_bootstrap.workload.quant import decode_stream_bytes as param_bytes

    def roofline(prefix, params, step_s):
        bytes_step = param_bytes(params)
        out.update({
            f"{prefix}_bytes_per_token": round(bytes_step / dbatch),
            f"{prefix}_achieved_gbps": round(bytes_step / step_s / 1e9, 1),
            f"{prefix}_hbm_roofline_frac": round(
                bytes_step / step_s / PEAK_HBM, 3),
        })

    step_s = decode_step_s(dparams)
    out.update({
        "decode_tokens_per_sec": round(dbatch / step_s, 1),
        "decode_step_ms": round(step_s * 1e3, 3),
    })
    roofline("decode", dparams, step_s)
    emit()
except Exception as e:  # noqa: BLE001
    out["decode_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

try:
    # Per-kernel roofline BEFORE the quantized decode sections: each
    # quantized matmul timed ALONE at the decode model's exact launch
    # shapes. The first EAGER call per shape runs the autotuner (2-3
    # (block_n, block_k) candidates on the chip, winner cached
    # process-wide + achieved-GB/s gauges set), so the jitted decode
    # traces below pick the tuned tilings up by shape. kernel_* keys are
    # first-class regression keys (gbps / roofline_frac suffixes).
    from tpu_bootstrap.workload import quant as _q

    def timed_kernel(prefix, fn, x, qw, iters=16):
        jax.block_until_ready(fn(x, qw))  # eager: autotunes + sets gauges

        @jax.jit
        def many(x, qw):
            def body(acc, _):
                return acc + jnp.sum(fn(x, qw).astype(jnp.float32)), None
            acc, _ = lax.scan(body, jnp.float32(0), None, length=iters)
            return acc

        float(many(x, qw))  # compile + warm
        t0 = time.time()
        float(many(x, qw))
        dt = (time.time() - t0) / iters
        moved = (_q.weight_stream_bytes(qw) + x.nbytes
                 + x.shape[0] * qw.q.shape[-1] * x.dtype.itemsize)
        out[f"kernel_{prefix}_ms"] = round(dt * 1e3, 4)
        out[f"kernel_{prefix}_achieved_gbps"] = round(moved / dt / 1e9, 1)
        out[f"kernel_{prefix}_hbm_roofline_frac"] = round(
            moved / dt / PEAK_HBM, 3)

    qblk = _q.quantize_block(dmaster["blocks"][0])
    xe = jax.random.normal(jax.random.PRNGKey(3), (dbatch, 1024), jnp.bfloat16)
    xf = jax.random.normal(jax.random.PRNGKey(4), (dbatch, 4096), jnp.bfloat16)
    timed_kernel("int8_qkv_fused", _q.int8_matmul, xe, qblk["wqkv"])
    timed_kernel("int8_up", _q.int8_matmul, xe, qblk["w_up"])
    timed_kernel("int8_down", _q.int8_matmul, xf, qblk["w_down"])
    timed_kernel("int8_head", _q.int8_matmul, xe,
                 _q.quantize_weight(dmaster["embed"].T))
    emit()
    q4blk = _q.quantize_block4(dmaster["blocks"][0])
    timed_kernel("int4_qkv_fused", _q.int4_matmul, xe, q4blk["wqkv"])
    timed_kernel("int4_up", _q.int4_matmul, xe, q4blk["w_up"])
    # Expert-stack kernel at a representative MoE shape (the bench model
    # is dense; the kernel's grid/pipeline behavior is what's measured).
    ew = _q.quantize_expert_weight(
        jax.random.normal(jax.random.PRNGKey(5), (8, 1024, 4096)))
    xew = jax.random.normal(jax.random.PRNGKey(6), (8, dbatch, 1024),
                            jnp.bfloat16)
    timed_kernel("int8_expert", _q.int8_expert_matmul, xew, ew)
    out["quant_tuned_blocks"] = ";".join(
        f"{k}={v}" for k, v in _q.tuned_blocks().items()) or "defaults"
    emit()
except Exception as e:  # noqa: BLE001
    out["kernel_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

try:
    # Same measurement with int8 weight-only quantized blocks (the
    # bandwidth-bound regime where halved weight bytes should show).
    from tpu_bootstrap.workload.quant import quantize_params

    qparams = quantize_params(dmaster)
    qstep_s = decode_step_s(qparams)
    out.update({
        "decode_int8_tokens_per_sec": round(dbatch / qstep_s, 1),
        "decode_int8_speedup": round(step_s / qstep_s, 3),
    })
    roofline("decode_int8", qparams, qstep_s)
except Exception as e:  # noqa: BLE001
    out["decode_int8_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Each decode variant below fails ALONE: round 5's int4 Mosaic crash sat
# in the shared try and took the xent/int8kv/gqa keys down with it — a
# NameError from a dead prerequisite becomes that section's own error
# key instead of a lost section.
try:
    # int4 weight-only (VERDICT r3 item 8): 0.5 bytes/element through
    # the group-scaled nibble-packed kernel; head stays int8 (the
    # softmax decides there). Plus the quality ladder at CHECKPOINT size
    # — mean next-token xent delta vs the f32 master on the same batch
    # (random-init weights: this measures the FORMAT's noise at scale,
    # not task degradation; the trained-model task-level numbers live in
    # the quality section above).
    from tpu_bootstrap.workload.quant import quantize_params4, quantize_weight4

    qparams4 = quantize_params4(dmaster)
    q4step_s = decode_step_s(qparams4)
    out.update({
        "decode_int4_tokens_per_sec": round(dbatch / q4step_s, 1),
        "decode_int4_speedup": round(step_s / q4step_s, 3),
    })
    roofline("decode_int4", qparams4, q4step_s)
    emit()

    # ONE jitted program per scoring call (quality.score): the eager
    # prefill's per-op program spray crashed the tunnel's compile helper
    # (exit 1) — the reason these keys never appeared in r3/r4 BENCH.
    from tpu_bootstrap.workload.quality import score as _score

    def mean_xent(params):
        toks = jax.random.randint(jax.random.PRNGKey(9), (dbatch, 65), 0,
                                  dcfg.vocab_size)
        return float(_score(params, toks, dcfg)[0])

    xb = mean_xent(dmaster)
    out.update({
        "quant_xent_f32": round(xb, 4),
        "quant_xent_delta_int8": round(abs(mean_xent(qparams) - xb), 4),
        "quant_xent_delta_int4": round(abs(mean_xent(qparams4) - xb), 4),
        # int4 head: reuse the already-quantized blocks, swap only the
        # head copy (re-quantizing every block would re-pay the whole
        # device transfer inside the timeout-sensitive decode section).
        "quant_xent_delta_int4_head4": round(abs(mean_xent(
            {**qparams4, "lm_head": quantize_weight4(dmaster["embed"].T)})
            - xb), 4),
    })
except Exception as e:  # noqa: BLE001
    out["decode_int4_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

try:
    # int8 KV cache ON TOP of int8 weights: after weight quantization the
    # remaining per-step HBM read is the cache; int8 KV halves it (the
    # decode.init_cache quantized layout).
    kvstep_s = decode_step_s(qparams, kv_quant=True)
    out.update({
        "decode_int8kv_tokens_per_sec": round(dbatch / kvstep_s, 1),
        "decode_int8kv_speedup": round(step_s / kvstep_s, 3),
    })
    emit()

    # Grouped-query attention: 4 KV heads instead of 16 shrinks the KV
    # cache 4x — the other decode-bandwidth lever this framework ships.
    import dataclasses
    gcfg = dataclasses.replace(dcfg, num_kv_heads=4)
    gparams = to_bf16(init_params(gcfg, jax.random.PRNGKey(0)))
    gstep_s = decode_step_s(gparams, gcfg)
    out.update({
        "decode_gqa4_tokens_per_sec": round(dbatch / gstep_s, 1),
        "decode_gqa4_speedup": round(step_s / gstep_s, 3),
    })
    roofline("decode_gqa4", gparams, gstep_s)
except Exception as e:  # noqa: BLE001
    out["decode_kv_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Continuous batching (serving.serve): wall-clock tokens/s through the
# slot pool on a ragged synthetic workload, plain decode vs the
# speculative verify-commit composition — the two serving levers
# together. The analytic accounting (slot utilization, committed tokens
# per target stream) rides along so the chip numbers stay interpretable:
# spec mode's wall clock only wins when mean committed/stream outruns
# the draft's overhead, which random-init acceptance rarely buys —
# tokens-per-stream is the structural number, wall-clock the honest one.
try:
    from tpu_bootstrap.workload.serving import Request, serve

    import numpy as _np

    def serve_workload(n=24, seed=7):
        # FIXED seed, fresh rng per call: every serving comparator
        # (tokens/s, admit ratio, the prefix keys below) must judge the
        # IDENTICAL traffic on every bench run, or --check would gate
        # RNG drift as regression.
        rng = _np.random.default_rng(seed)
        return [Request(rid=i,
                        tokens=rng.integers(1, dcfg.vocab_size, 8).tolist(),
                        max_new=int(rng.choice([4, 8, 16, 32])))
                for i in range(n)]

    def timed_serve(**kw):
        serve(dparams, dcfg, serve_workload(), 8, **kw)  # compile all shapes
        stats = {}
        t0 = time.time()
        done = serve(dparams, dcfg, serve_workload(), 8, stats=stats, **kw)
        dt = time.time() - t0
        toks = sum(len(v) for v in done.values())
        return toks / dt, stats

    plain_tps, pstats = timed_serve()
    out.update({
        "serve_tokens_per_sec": round(plain_tps, 1),
        "serve_slot_utilization": round(
            pstats["active_slot_steps"] / max(pstats["slot_steps"], 1), 3),
    })
    emit()
    spec_tps, sstats = timed_serve(draft_params=qparams, draft_cfg=dcfg,
                                   gamma=4)
    out.update({
        "serve_spec_tokens_per_sec": round(spec_tps, 1),
        "serve_spec_committed_per_stream": round(
            sstats["committed_tokens"] / max(sstats["verify_rounds"], 1), 2),
    })
    emit()
    # Resident-cache engine: the same workload without history replay —
    # per-row frontiers, one admission prefill per request. The replay
    # pool re-prefills every active history each round (its
    # replayed_tokens counts it); resident's speedup is that cost
    # removed from the wall clock.
    res_tps, resstats = timed_serve(resident=True)
    out.update({
        "serve_resident_tokens_per_sec": round(res_tps, 1),
        "serve_resident_speedup": round(res_tps / plain_tps, 3),
        "serve_replayed_tokens": pstats.get("replayed_tokens", 0),
        "serve_resident_prefill_tokens": resstats.get("prefill_tokens", 0),
    })
    emit()
    # Per-row speculative on the resident engine: one target weight
    # stream per verify round, each row committing its OWN accepted
    # count (no lockstep min) — the committed-per-stream number is
    # batch-aggregate and should beat the replay pool's lockstep figure.
    rs_tps, rsstats = timed_serve(resident=True, draft_params=qparams,
                                  draft_cfg=dcfg, gamma=4)
    out.update({
        "serve_resident_spec_tokens_per_sec": round(rs_tps, 1),
        "serve_resident_spec_committed_per_stream": round(
            rsstats["committed_tokens"] / max(rsstats["verify_rounds"], 1),
            2),
    })
    emit()
    # Per-phase speculative timers (the serve_spec_* split the resident
    # spec round records): p50s from the registry of the run above, so
    # the wall-clock number is attributable to draft scan vs target
    # verify vs host commit instead of one opaque round time.
    from tpu_bootstrap import telemetry as _tele

    _sj = _tele.metrics().to_json()
    for _ph in ("draft", "verify", "commit"):
        _v = _sj.get(f"serve_spec_{_ph}_ms_p50")
        if _v is not None:
            out[f"serve_spec_{_ph}_p50_ms"] = round(_v, 2)
except Exception as e:  # noqa: BLE001
    out["serve_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Block-paged serving (serving.PagedPool): the same mixed-length
# workload through the shared KV-block pool. Three numbers tell the
# story: throughput (the gather/kernel path must not tax the steady
# state), the capacity ratio at EQUAL KV memory (the reason the engine
# exists — admission follows actual footprint, not slots x cap), and
# TTFT p99 under a concurrent-admission burst (chunked prefill
# interleaving vs the resident engine's admission-blocks-the-pool).
try:
    from tpu_bootstrap.workload.serving import PagedPool, ResidentPool

    pg_tps, pgstats = timed_serve(paged=True)
    out.update({
        "serve_paged_tokens_per_sec": round(pg_tps, 1),
        "serve_paged_speedup": round(pg_tps / plain_tps, 3),
        "kv_blocks_peak_frac": round(
            pgstats["blocks_peak"] / max(pgstats["blocks_total"], 1), 4),
    })
    emit()

    # Capacity at equal KV memory, counted analytically (no decode):
    # concurrent admissions of the bench workload into a paged pool
    # holding exactly the resident pool's 8 x max_seq_len tokens.
    res_cap = ResidentPool(dparams, dcfg, 8)
    _bs = int(os.environ.get("TPUBC_KV_BLOCK", "64"))
    pg_cap = PagedPool(dparams, dcfg, batch_size=64, block_size=_bs,
                       kv_blocks=8 * (-(-dcfg.max_seq_len // _bs)))
    n_res = n_pg = 0
    for r in serve_workload(64):
        if res_cap.admits(r):
            res_cap.admit(r); n_res += 1
    for r in serve_workload(64):
        if pg_cap.admits(r):
            pg_cap.admit(r); n_pg += 1
    out["serve_paged_admit_ratio"] = round(n_pg / max(n_res, 1), 2)
    del res_cap, pg_cap
    emit()

    # TTFT p99 under a 16-request burst of LONG prompts: every request
    # "arrives" at t0; the paged engine spreads prefill chunks across
    # rounds while earlier rows stream, the resident engine prefills
    # whole prompts at admission while the pool waits. One full warm
    # pass per engine first so compile time is not billed as TTFT.
    import numpy as _np2

    def ttft_workload(seed=11):
        # Same fixed-seed rule as serve_workload: the burst must be the
        # identical 16 requests every run for the gated TTFT p99.
        rng = _np2.random.default_rng(seed)
        return [Request(rid=i,
                        tokens=rng.integers(1, dcfg.vocab_size, 48).tolist(),
                        max_new=16)
                for i in range(16)]

    def ttft_p99(make_pool):
        for measured in (False, True):
            pool = make_pool()
            queue = ttft_workload()
            t0 = time.time()
            first = {}
            while queue or pool.has_active():
                while queue and pool.admits(queue[0]):
                    pool.admit(queue.pop(0))
                for rid, ev in pool.step_round().items():
                    if ev["new"] and rid not in first:
                        first[rid] = (time.time() - t0) * 1e3
            if measured:
                lat = sorted(first.values())
                return lat[min(int(0.99 * len(lat)), len(lat) - 1)]

    out["serve_ttft_p99_ms"] = round(ttft_p99(
        lambda: PagedPool(dparams, dcfg, 8)), 1)
    out["serve_resident_ttft_p99_ms"] = round(ttft_p99(
        lambda: ResidentPool(dparams, dcfg, 8)), 1)
except Exception as e:  # noqa: BLE001
    out["serve_paged_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Automatic prefix caching (serving.PagedPool prefix_cache): the
# north-star traffic shape — one shared system prompt, short unique
# tails — through the caching pool vs the SAME traffic with the cache
# disabled. Three stories: the aggregate hit rate (fraction of prompt
# tokens that skipped prefill — the capacity/FLOPs the cache returns),
# throughput speedup at identical traffic, and cached-vs-cold TTFT p50
# (the latency a warm prefix buys a request). Hit rate and cached TTFT
# are --check HARD gates alongside the paged SLO pair. Generators are
# fixed-seed (fresh rng per call) so these keys are apples-to-apples
# across runs.
try:
    from tpu_bootstrap.workload.serving import PagedPool as _PfxPool

    import numpy as _np3

    def prefix_workload(n=24, seed=13):
        # 192-token system prompt = three FULL default-size (64) blocks
        # — only whole blocks are content-addressable, so the shared
        # prefix must span block boundaries to be shareable at all.
        rng = _np3.random.default_rng(seed)
        sysp = rng.integers(1, dcfg.vocab_size, 192).tolist()
        return [Request(rid=i,
                        tokens=sysp
                        + rng.integers(1, dcfg.vocab_size, 8).tolist(),
                        max_new=16)
                for i in range(n)]

    def timed_prefix(**kw):
        serve(dparams, dcfg, prefix_workload(), 8, paged=True, **kw)
        stats = {}
        t0 = time.time()
        done = serve(dparams, dcfg, prefix_workload(), 8, paged=True,
                     stats=stats, **kw)
        return (sum(len(v) for v in done.values()) / (time.time() - t0),
                stats)

    warm_tps, wstats = timed_prefix()
    cold_tps, _cstats = timed_prefix(prefix_cache=False)
    out.update({
        "serve_prefix_hit_rate": round(
            wstats["prefix_hit_tokens"] / max(wstats["prompt_tokens"], 1),
            4),
        "serve_prefix_tokens_per_sec_speedup": round(
            warm_tps / max(cold_tps, 1e-9), 3),
        "serve_prefix_cow_copies": wstats["cow_copies"],
    })
    emit()

    def prefix_ttft_p50(prefix_cache):
        # One full warm pass per config (compile time is not TTFT);
        # inside the measured pass, a single request drains first so
        # the shared prompt is cached before the burst arrives — the
        # steady state of a long-lived serving slice.
        for measured in (False, True):
            pool = _PfxPool(dparams, dcfg, 8, prefix_cache=prefix_cache)
            pool.admit(prefix_workload(1)[0])
            while pool.has_active():
                pool.step_round()
            queue = prefix_workload(16)
            t0 = time.time()
            first = {}
            while queue or pool.has_active():
                while queue and pool.admits(queue[0]):
                    pool.admit(queue.pop(0))
                for rid, ev in pool.step_round().items():
                    if ev["new"] and rid not in first:
                        first[rid] = (time.time() - t0) * 1e3
            if measured:
                lat = sorted(first.values())
                return lat[len(lat) // 2]

    out["serve_cached_ttft_p50_ms"] = round(prefix_ttft_p50(True), 1)
    out["serve_cold_ttft_p50_ms"] = round(prefix_ttft_p50(False), 1)
except Exception as e:  # noqa: BLE001
    out["serve_prefix_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Host-memory KV tier (serving.HostBlockPool): the long-tail shape the
# tier exists for — a working set of DISTINCT multi-block prefixes
# re-arriving after the HBM cache let them go. Phase A fills the cache,
# a forced demotion sweep parks every cached block on host, phase B
# replays the same prompts: every prefix plan is then a host-tier hit
# served by one batched host->device promotion instead of re-prefill.
# serve_host_hit_rate (--check HARD alongside the prefix pair) is the
# fraction of phase-B prompt tokens the tier returned; the
# restore-vs-recompute p50 pair prices the swap arm against
# evict-and-recompute on the SAME preempting burst (tier off vs on at
# equal KV memory) — the measured inequality the per-victim cost-model
# decision rides on, and serve_effective_cache_blocks is the hittable
# capacity the DRAM tier adds on top of HBM.
try:
    from tpu_bootstrap.workload.serving import (
        PagedPool as _HtPool,
        Scheduler as _HtSched,
    )

    from tpu_bootstrap import telemetry as _httel
    import numpy as _nph

    _hbs = 16  # same finer-than-default granularity story as overcommit

    def lt_prompts(n=10, seed=37):
        # Fixed seed, fresh rng per call (the serving comparator rule):
        # 40 tokens = two FULL 16-token blocks (only whole blocks are
        # content-addressable) + an 8-token tail that stays cold.
        rng = _nph.random.default_rng(seed)
        return [rng.integers(1, dcfg.vocab_size, 40).tolist()
                for _ in range(n)]

    def _ht_drive(pool, reqs):
        queue = list(reqs)
        while queue or pool.has_active():
            while queue and pool.admits(queue[0]):
                pool.admit(queue.pop(0))
            pool.step_round()

    _restore_ms: list = []

    def _time_restores(pool):
        real = pool._host_restore

        def timed(ids, entries):
            t0 = time.time()
            moved = real(ids, entries)
            _restore_ms.append((time.time() - t0) * 1e3)
            return moved

        pool._host_restore = timed

    lt_pool = _HtPool(dparams, dcfg, 8, block_size=_hbs, kv_blocks=64,
                      host_blocks=64)
    _ht_drive(lt_pool, [Request(rid=i, tokens=p, max_new=8)
                        for i, p in enumerate(lt_prompts())])
    lt_pool.demote_lru(lt_pool.allocator.cached())  # the eviction sweep
    _time_restores(lt_pool)
    _hh0 = lt_pool.stats.get("host_hit_tokens", 0)
    _pt0 = lt_pool.stats["prompt_tokens"]
    _ht_drive(lt_pool, [Request(rid=100 + i, tokens=p, max_new=8)
                        for i, p in enumerate(lt_prompts())])
    out.update({
        "serve_host_hit_rate": round(
            (lt_pool.stats.get("host_hit_tokens", 0) - _hh0)
            / max(lt_pool.stats["prompt_tokens"] - _pt0, 1), 4),
        "serve_effective_cache_blocks":
            lt_pool.allocator.cached() + len(lt_pool.host),
    })
    emit()

    def ht_burst(seed=43):
        rng = _nph.random.default_rng(seed)
        return [Request(rid=200 + i,
                        tokens=rng.integers(1, dcfg.vocab_size,
                                            8).tolist(),
                        max_new=24)
                for i in range(12)]

    def _ht_preempt_run(host_blocks):
        # Tight pool + low EMA seed: the burst MUST preempt, and every
        # resume is either a measured promotion transfer (tier on) or a
        # re-prefill priced at the engine's own observed prefill
        # throughput (tier off) — the same numbers the engine feeds the
        # serve_preempt_cost arms.
        pool = _HtPool(dparams, dcfg, 8, block_size=_hbs, kv_blocks=12,
                       host_blocks=host_blocks)
        sched = _HtSched(pool, overcommit=True, expected_new=2)
        rec_ms: list = []
        real_admit = pool.admit

        def admit(r, **kw):
            pre = _httel.metrics().to_json().get(
                "serve_preempt_recompute_tokens_total", 0)
            real_admit(r, **kw)
            d = _httel.metrics().to_json().get(
                "serve_preempt_recompute_tokens_total", 0) - pre
            if d and pool._prefill_ms_per_tok is not None:
                rec_ms.append(d * pool._prefill_ms_per_tok)

        pool.admit = admit
        if host_blocks:
            _time_restores(pool)
        for r in ht_burst():
            sched.submit(r)
        while sched.pending() or pool.has_active():
            sched.step()
        return pool, rec_ms

    _off_pool, _rec_ms = _ht_preempt_run(0)
    _on_pool, _ = _ht_preempt_run(64)

    def _ht_p50(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    out.update({
        "serve_preempt_recompute_ms_p50":
            round(_ht_p50(_rec_ms), 3) if _rec_ms else -1.0,
        "serve_swap_restore_ms_p50":
            round(_ht_p50(_restore_ms), 3) if _restore_ms else -1.0,
        "serve_swap_probe_preempts":
            _on_pool.stats.get("swap_preempts", 0),
    })
    if _rec_ms and _restore_ms:
        out["serve_swap_restore_speedup"] = round(
            _ht_p50(_rec_ms) / max(_ht_p50(_restore_ms), 1e-9), 3)
except Exception as e:  # noqa: BLE001
    out["serve_host_tier_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Overcommit scheduler (serving.Scheduler): an overcommitted burst —
# mixed budgets whose WHOLE footprints structurally over-subscribe a
# tight block pool — through expected-footprint admission vs PR 5's
# whole-footprint refusal admission at EQUAL KV memory. The story the
# gate watches: serve_admit_ratio (concurrent admissions, overcommit /
# refusal — reservation following expectation instead of worst case is
# the whole point), burst TTFT p99 under each policy (queued requests
# start later; overcommit must not give the win back to preemption
# thrash), queue-wait p50 and the preemption count under the
# overcommitted run. serve_admit_ratio is a --check HARD key alongside
# the paged/prefix SLO pairs.
try:
    from tpu_bootstrap.workload.serving import (
        PagedPool as _OcPool,
        Scheduler as _OcSched,
    )

    import numpy as _np4

    def burst_workload(n=24, seed=19):
        # Fixed seed, fresh rng per call (the serving comparator rule):
        # 8-token prompts under ONE declared budget (64) far above the
        # typical completion — the attractor eos below makes most rows
        # finish far short of it, the declared-vs-actual gap refusal
        # admission wastes capacity on (PAPERS.md's vLLM entry).
        rng = _np4.random.default_rng(seed)
        return [Request(rid=i,
                        tokens=rng.integers(1, dcfg.vocab_size, 8).tolist(),
                        max_new=64)
                for i in range(n)]

    # Greedy decode from this fixed random init converges to an
    # attractor token within a few steps; serving it as eos_id gives
    # the burst DETERMINISTIC early finishers (true lengths mostly
    # single-digit against the 64 budget) without a trained
    # checkpoint. A row that never emits it just runs to budget — the
    # mix is the premise, not a pin.
    _oc_eos = int(_np4.bincount(_np4.asarray(generate(
        dparams, jnp.asarray([r.tokens for r in burst_workload(4)]),
        dcfg, 16))[:, -1]).argmax())

    # 16-token blocks, NOT the serving default of 64: at block 64 an
    # 8-token-prompt burst rounds every whole footprint down to 1-2
    # blocks and expected-footprint admission has nothing to save —
    # the overcommit win lives at footprint granularity finer than the
    # declared budget (whole footprint 5 blocks vs the EMA seed's 2).
    _obs = 16
    # Tight at EQUAL memory for both policies: ~1/3 of the burst's full
    # footprint, so refusal admission must queue most of it.
    _oc_blocks = max(2, sum(-(-(8 + r.max_new) // _obs)
                            for r in burst_workload()) // 3)

    def _oc_pool():
        return _OcPool(dparams, dcfg, batch_size=24, block_size=_obs,
                       kv_blocks=_oc_blocks, eos_id=_oc_eos)

    def concurrent_admits(overcommit):
        pool = _oc_pool()
        sched = _OcSched(pool, overcommit=overcommit)
        n = 0
        for r in burst_workload():
            res = sched.expected_new(r)
            if pool.admits(r, reserve_new=res):
                pool.admit(r, reserve_new=res)
                n += 1
        return n

    n_oc = concurrent_admits(True)
    n_ref = concurrent_admits(False)
    out["serve_admit_ratio"] = round(n_oc / max(n_ref, 1), 2)
    emit()

    def burst_ttft_p99(overcommit):
        # One full warm pass per policy (compile time is not TTFT).
        for measured in (False, True):
            pool = _oc_pool()
            sched = _OcSched(pool, overcommit=overcommit)
            t0 = time.time()
            first = {}
            for r in burst_workload():
                sched.submit(r)
            while sched.pending() or pool.has_active():
                for rid, ev in sched.step().items():
                    if ev["new"] and rid not in first:
                        first[rid] = (time.time() - t0) * 1e3
            if measured:
                lat = sorted(first.values())
                return (lat[min(int(0.99 * len(lat)), len(lat) - 1)],
                        pool, sched)

    oc_ttft, oc_pool, oc_sched = burst_ttft_p99(True)
    ref_ttft, _, _ = burst_ttft_p99(False)
    out.update({
        "serve_overcommit_ttft_p99_ms": round(oc_ttft, 1),
        "serve_refusal_ttft_p99_ms": round(ref_ttft, 1),
        "serve_queue_wait_p50_ms": round(oc_sched.queue_wait_p50_ms(), 2),
        "serve_preempt_total": oc_pool.stats["preemptions"],
        "serve_overcommit_grown_blocks": oc_pool.stats["grown_blocks"],
    })
    emit()

    # Preemption COST (not just count — the serve_preempt_total
    # satellite): a deliberately tight pool (EMA seeded far below the
    # budgets, ~the preemption-exactness tests' shape) that MUST
    # preempt, so the evict-and-recompute price keys are live:
    # recompute tokens actually re-prefilled at resume (cache hits
    # already deducted), the preempt->resume wall gap, and the
    # phase-share attribution of where the burst's request time went.
    from tpu_bootstrap import telemetry as _tel

    _mj0 = _tel.metrics().to_json()
    _rc0 = _mj0.get("serve_preempt_recompute_tokens_total", 0)
    tight_pool = _OcPool(dparams, dcfg, batch_size=16, block_size=_obs,
                         kv_blocks=16, eos_id=_oc_eos)
    tight_sched = _OcSched(tight_pool, overcommit=True, expected_new=2)
    for r in burst_workload(12, seed=29):
        tight_sched.submit(r)
    _oc_ntok = 0
    while tight_sched.pending() or tight_pool.has_active():
        for _rid, _ev in tight_sched.step().items():
            _oc_ntok += len(_ev["new"])
    _mj1 = _tel.metrics().to_json()
    out.update({
        "serve_preempt_probe_total": tight_pool.stats["preemptions"],
        "serve_preempt_recompute_tokens_total":
            _mj1.get("serve_preempt_recompute_tokens_total", 0) - _rc0,
        "serve_resume_gap_p50_ms":
            round(_mj1.get("serve_resume_gap_ms_p50", -1.0), 3),
    })
    # Device-time ledger over the same tight run: the attribution plane's
    # bench keys. The driver loop is back-to-back step() calls, so
    # busy_frac here is an upper bound (~1.0) — the key guards the ledger
    # staying live and conservative, not a latency story. MFU uses the
    # same flops_model pricing the serving and train planes share.
    _led = tight_sched.ledger
    out.update({
        "serve_engine_busy_frac":
            round(_led["busy_ms"] / max(_led["wall_ms"], 1e-9), 4),
        "serve_mfu": round(
            _led["flops"]
            / (max(_led["wall_ms"], 1e-9) * 1e-3
               * _tel.peak_tflops() * 1e12), 9),
        "serve_device_ms_per_token":
            round(_led["attributed_ms"] / max(_oc_ntok, 1), 4),
        "serve_ledger_conserved": bool(
            abs(_led["busy_ms"] + _led["idle_ms"] - _led["wall_ms"]) < 0.05
            and abs(_led["attributed_ms"] + _led["unattributed_ms"]
                    - _led["busy_ms"]) < 0.05),
    })
    out.update({f"serve_phase_share_{k}": v
                for k, v in tight_sched.log.phase_shares().items()})
    # One joined preempted-then-resumed timeline must exist in the
    # flight recorder (the acceptance criterion /requestz + Perfetto
    # ride the same record for).
    _rz = tight_sched.log.snapshot()
    out["serve_preempted_timelines"] = sum(
        1 for r in _rz["requests"]
        if r["preemptions"] > 0 and r["state"] == "retired"
        and r["legs"] >= 2)
    emit()

    # Event-log overhead guard: the SAME fixed workload with the
    # request-event log on vs off. Streams must be byte-identical
    # (also test-pinned in tests/test_requestz.py) and the tokens/s
    # delta is the event log's whole price — the <2% budget the ISSUE
    # pins (wall-clock on shared CI is noisy; the key is the record,
    # the test pins the byte-identity that actually guards serving).
    def _ev_serve():
        t0 = time.time()
        d = serve(dparams, dcfg, burst_workload(12, seed=23), 8,
                  paged=True, block_size=_obs, eos_id=_oc_eos)
        dt = time.time() - t0
        return d, sum(len(v) for v in d.values()) / max(dt, 1e-9)

    _ev_serve()  # warm the compile caches out of the comparison
    on_done, on_tps = _ev_serve()
    os.environ["TPUBC_REQUEST_EVENTS"] = "0"
    try:
        off_done, off_tps = _ev_serve()
    finally:
        os.environ.pop("TPUBC_REQUEST_EVENTS", None)
    out.update({
        "serve_tokens_per_sec_events_on": round(on_tps, 1),
        "serve_tokens_per_sec_events_off": round(off_tps, 1),
        "serve_events_overhead_frac":
            round(max(0.0, 1.0 - on_tps / max(off_tps, 1e-9)), 4),
        "serve_events_streams_identical": on_done == off_done,
    })
except Exception as e:  # noqa: BLE001
    out["serve_overcommit_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Chaos (fault-injection ISSUE): the failure path's own SLO numbers.
# A pinned multi-shot fault schedule (two device aborts + one allocator
# breach) runs through crash-is-preemption recovery mid-burst;
# serve_chaos_goodput_frac is the fraction of that burst completing
# within its declared deadline ANYWAY — the --check-gated promise that
# recovery keeps serving, not just avoids crashing. Alongside it: the
# recovery price (quarantine + cache salvage + requeue, p50 ms), the
# deadline enforcement count from a half-hopeless burst, and the wall
# clock of a graceful drain with a live resident.
try:
    from tpu_bootstrap import telemetry as _tel5
    from tpu_bootstrap.workload import faults as _faults
    from tpu_bootstrap.workload.serving import (
        PagedPool as _ChPool,
        Scheduler as _ChSched,
    )

    import numpy as _np5

    def chaos_burst(n=10, seed=31, deadline_s=None):
        rng = _np5.random.default_rng(seed)
        dl = (time.monotonic() + deadline_s) if deadline_s else None
        return [Request(rid=i,
                        tokens=rng.integers(1, dcfg.vocab_size, 8).tolist(),
                        max_new=24, deadline=dl)
                for i in range(n)]

    def _chaos_drive(sched, pool, reqs):
        done = {}
        for r in reqs:
            sched.submit(r)
        while sched.pending() or pool.has_active():
            for rid, ev in sched.step().items():
                if ev["done"]:
                    done[rid] = ev
        return done

    # Recovery probe: every request carries a generous-but-real SLO;
    # the pinned schedule aborts two rounds and breaches one alloc.
    _mj0 = _tel5.metrics().to_json()
    _ch_eos = globals().get("_oc_eos")  # None if the oc section failed
    pool = _ChPool(dparams, dcfg, batch_size=8, block_size=16,
                   kv_blocks=64, eos_id=_ch_eos)
    sched = _ChSched(pool)
    reqs = chaos_burst(10, seed=31, deadline_s=120.0)
    _faults.install("pool.device:1:2,pool.device:1:6,alloc:1:4")
    try:
        done = _chaos_drive(sched, pool, reqs)
    finally:
        _faults.install(None)
    _mj1 = _tel5.metrics().to_json()
    ok = sum(1 for ev in done.values()
             if not ev.get("deadline") and not ev.get("error"))
    out.update({
        "serve_chaos_goodput_frac": round(ok / len(reqs), 3),
        "serve_chaos_recoveries": sched.stats["recoveries"],
        "serve_recovery_ms_p50":
            round(_mj1.get("serve_recovery_ms_p50", -1.0), 3),
    })
    emit()

    # Deadline enforcement: half the burst arrives already hopeless
    # (expired SLO), half generous — the sheds must be exactly the
    # hopeless half, at queue-shed price (no rounds spent on them).
    pool = _ChPool(dparams, dcfg, batch_size=8, block_size=16,
                   kv_blocks=64, eos_id=_ch_eos)
    sched = _ChSched(pool)
    hopeless = chaos_burst(5, seed=33, deadline_s=-1.0)
    fine = [Request(rid=100 + r.rid, tokens=r.tokens, max_new=r.max_new)
            for r in chaos_burst(5, seed=34)]
    _chaos_drive(sched, pool, hopeless + fine)
    out["serve_deadline_shed_total"] = sched.stats["deadline_shed"]
    emit()

    # Drain: a live ingress with a resident mid-decode; drain() wall
    # clock covers flush + quarantine + the final draining chunks.
    import json as _json5
    import threading as _th5
    import urllib.request as _url5

    from tpu_bootstrap.workload.ingress import IngressServer as _ChIngress

    srv = _ChIngress(dparams, dcfg, port=0, batch_size=4, paged=True,
                     block_size=16, kv_blocks=64,
                     host="127.0.0.1").start()
    try:
        def _chaos_post(body):
            rq = _url5.Request(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                data=_json5.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with _url5.urlopen(rq, timeout=120) as resp:
                return [_json5.loads(ln) for ln in resp if ln.strip()]

        _chaos_post({"tokens": [2, 3], "max_new": 2})  # pay the jit
        lines = []
        t = _th5.Thread(target=lambda: lines.extend(
            _chaos_post({"tokens": [1, 2, 3], "max_new": 48})))
        t.start()
        while not any(ln.get("tokens") for ln in lines):
            time.sleep(0.005)
        out["serve_drain_ms"] = round(srv.drain(timeout_ms=250), 2)
        t.join(timeout=60)
    finally:
        srv.stop()
except Exception as e:  # noqa: BLE001
    out["serve_chaos_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Fleet plane (fleetz ISSUE): the aggregator's two shipped numbers. A
# two-replica mini-fleet (same weights, independent pools) serves a
# shared-prefix prompt on replica A only; fleet_digest_match_uplift is
# how many leading prompt blocks A's published cache digest scores
# above cold B's — the router's placement signal, and the gate that the
# digest actually distinguishes a warm replica from a cold one.
# fleet_scrape_staleness_p99_ms is the aggregator's own freshness tail
# across the poll cycles — the /fleetz pane must not go stale while
# claiming to watch the fleet.
try:
    import json as _json6
    import urllib.request as _url6

    from tpu_bootstrap.workload import serving as _srv6
    from tpu_bootstrap.workload.fleetz import FleetAggregator as _Fleet
    from tpu_bootstrap.workload.ingress import IngressServer as _FlIngress

    _fl_a = _FlIngress(dparams, dcfg, port=0, batch_size=4, paged=True,
                       block_size=16, kv_blocks=64,
                       host="127.0.0.1").start()
    _fl_b = _FlIngress(dparams, dcfg, port=0, batch_size=4, paged=True,
                       block_size=16, kv_blocks=64,
                       host="127.0.0.1").start()
    _fl_agg = None
    try:
        def _fl_post(port, toks, n=8):
            rq = _url6.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=_json6.dumps({"tokens": toks, "max_new": n,
                                   "stream": False}).encode(),
                headers={"Content-Type": "application/json"})
            with _url6.urlopen(rq, timeout=120) as resp:
                return _json6.loads(resp.read())

        _fl_prompt = list(range(1, 49))
        _fl_post(_fl_a.port, _fl_prompt)  # warm A: registers the prefix
        _fl_post(_fl_a.port, _fl_prompt)  # hit it: blocks provably shared
        _fl_agg = _Fleet(
            [f"127.0.0.1:{_fl_a.port}", f"127.0.0.1:{_fl_b.port}"],
            port=0, host="127.0.0.1", poll_s=0.1).start()
        _fl_t0 = time.time()
        while time.time() - _fl_t0 < 30:
            fz = _fl_agg.fleetz_json()
            if fz["fleet"]["healthy"] == 2 and fz["fleet"]["digest_blocks"]:
                break
            time.sleep(0.05)
        fz = _fl_agg.fleetz_json()
        _fl_da = (fz["replicas"][f"127.0.0.1:{_fl_a.port}"]["cache_digest"]
                  or {})
        _fl_db = (fz["replicas"][f"127.0.0.1:{_fl_b.port}"]["cache_digest"]
                  or {})
        out.update({
            "fleet_digest_match_uplift":
                _srv6.digest_match_len(_fl_prompt, _fl_da)
                - _srv6.digest_match_len(_fl_prompt, _fl_db),
            "fleet_scrape_staleness_p99_ms": round(
                _fl_agg.reg.quantile("fleet_scrape_staleness_ms", 0.99), 3),
            "fleet_replicas_healthy": fz["fleet"]["healthy"],
            "fleet_digest_blocks": fz["fleet"]["digest_blocks"],
        })
    finally:
        if _fl_agg is not None:
            _fl_agg.stop()
        _fl_a.stop()
        _fl_b.stop()
except Exception as e:  # noqa: BLE001
    out["fleet_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Fleet router (router ISSUE): the front door's two shipped numbers.
# fleet_route_hit_uplift is cached prompt tokens served under the
# router's digest placement over the same burst dealt round-robin —
# the entire reason cache-aware placement exists, and it must beat 1.0
# or the router is a load balancer with extra steps.
# fleet_chaos_goodput_frac is the survivor-fleet goodput after a
# SIGKILL takes a subprocess replica out mid-burst: every in-flight
# request must still reach exactly one terminal outcome and the next
# wave must complete clean — the bounded-goodput-dip contract.
# fleet_scale_up_reaction_ms and the dip/recovery numbers ride along
# as soft telemetry.
try:
    import json as _json8
    import signal as _sig8
    import subprocess as _sub8
    import threading as _th8
    import urllib.request as _url8

    from tpu_bootstrap.workload.ingress import IngressServer as _RtIngress
    from tpu_bootstrap.workload.router import (
        AutoscaleController as _RtCtl, FleetRouter as _RtRouter)

    def _rt_req(port, body, timeout=300):
        rq = _url8.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=_json8.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        if not body.get("stream"):
            with _url8.urlopen(rq, timeout=timeout) as resp:
                return _json8.loads(resp.read())
        with _url8.urlopen(rq, timeout=timeout) as resp:
            return [_json8.loads(ln) for ln in resp if ln.strip()]

    _rt_a = _RtIngress(dparams, dcfg, port=0, batch_size=4, paged=True,
                       block_size=16, kv_blocks=64,
                       host="127.0.0.1").start()
    _rt_b = _RtIngress(dparams, dcfg, port=0, batch_size=4, paged=True,
                       block_size=16, kv_blocks=64,
                       host="127.0.0.1").start()
    _rt = None
    try:
        _rt_prompt = list(range(5, 53))  # 3 full 16-token blocks
        # Pay both engines' jit, then warm ONLY A with the prefix.
        _rt_req(_rt_a.port, {"tokens": [2, 3], "max_new": 2,
                             "stream": False})
        _rt_req(_rt_b.port, {"tokens": [2, 3], "max_new": 2,
                             "stream": False})
        _rt_req(_rt_a.port, {"tokens": _rt_prompt, "max_new": 4,
                             "stream": False})

        # Round-robin baseline: the same warm-prompt burst dealt
        # blindly across the pair pays B's cold prefill.
        _rr_ports = [_rt_a.port, _rt_b.port]
        _rr_cached = sum(
            _rt_req(_rr_ports[i % 2],
                    {"tokens": _rt_prompt, "max_new": 4,
                     "stream": False}).get("cached_tokens") or 0
            for i in range(6))

        _rt = _RtRouter([f"127.0.0.1:{_rt_a.port}",
                         f"127.0.0.1:{_rt_b.port}"],
                        port=0, host="127.0.0.1", scrape_s=0.1,
                        stale_s=10.0).start()
        _rt_t0 = time.time()
        while time.time() - _rt_t0 < 30:
            rz = _rt.routerz_json()
            if all(e["digest_age_ms"] is not None
                   for e in rz["replicas"].values()):
                break
            time.sleep(0.05)
        _route_cached = sum(
            _rt_req(_rt.port, {"tokens": _rt_prompt, "max_new": 4,
                               "stream": False}).get("cached_tokens")
            or 0 for i in range(6))
        out.update({
            "fleet_route_hit_uplift": round(
                _route_cached / max(_rr_cached, 1), 3),
            "fleet_route_cached_tokens": _route_cached,
            "fleet_rr_cached_tokens": _rr_cached,
        })

        # Scale-up reaction at the bench cadence: canned firing burn
        # through the real controller tick until the driver is told to
        # grow the fleet.
        class _RecDrv:
            at = None

            def scale_to(self, n):
                self.at = time.time()

        _rt.autoscaler = _RtCtl(1, 3, up_ticks=2, cooldown_s=0.0)
        _rt.driver = _drv = _RecDrv()
        _burn = {"r": {"ttft_p99": {"burn": 9.0, "firing": True,
                                    "windows": {"300s": 9.0}}}}
        _sc_t0 = time.time()
        while _drv.at is None and time.time() - _sc_t0 < 10:
            _rt.autoscale_once(burn=_burn)
            time.sleep(0.05)
        if _drv.at is not None:
            out["fleet_scale_up_reaction_ms"] = round(
                (_drv.at - _sc_t0) * 1e3, 1)
        _rt.driver = _rt.autoscaler = None

        # Kill-a-replica: a SIGKILL-able subprocess victim joins the
        # fleet (pinned to CPU — it is there to die, not to compute),
        # a burst straddles the kill, and the next wave must run clean
        # on the survivor.
        _victim = _sub8.Popen(
            [sys.executable, "-c", (
                "import os\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                "import jax\n"
                "from tpu_bootstrap.workload.ingress import "
                "IngressServer\n"
                "from tpu_bootstrap.workload.model import "
                "ModelConfig, init_params\n"
                "cfg = ModelConfig(vocab_size=32, num_layers=1, "
                "num_heads=2, head_dim=8, embed_dim=16, mlp_dim=32, "
                "max_seq_len=64)\n"
                "srv = IngressServer(init_params(cfg, "
                "jax.random.PRNGKey(1)), cfg, port=0, batch_size=2, "
                "paged=True, kv_blocks=24, block_size=8, "
                "host='127.0.0.1')\n"
                "srv.serve_forever()\n")],
            stdout=_sub8.PIPE, stderr=_sub8.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        _v_port = None
        _v_t0 = time.time()
        while time.time() - _v_t0 < 240:
            ln = _victim.stdout.readline()
            if not ln:
                break
            if "ingress: serving on :" in ln:
                _v_port = int(ln.split(":")[-1].split()[0].rstrip(")"))
                break
        if _v_port is None:
            raise RuntimeError("chaos victim replica never came up")
        _rt_req(_v_port, {"tokens": [2, 3], "max_new": 2,
                          "stream": False})  # pay the victim's jit
        _rt.add_replica(f"127.0.0.1:{_v_port}")
        while time.time() - _v_t0 < 270:
            rz = _rt.routerz_json()["replicas"]
            if rz[f"127.0.0.1:{_v_port}"]["digest_age_ms"] is not None:
                break
            time.sleep(0.05)

        def _rt_burst(n, tag):
            res = [None] * n
            ts = []
            for i in range(n):
                def run(i=i):
                    try:
                        res[i] = _rt_req(
                            _rt.port,
                            {"tokens": [1, 2, 3 + i % 5],
                             "max_new": 16, "stream": True,
                             "request_id": f"bench-{tag}-{i}"})
                    except Exception as e:  # noqa: BLE001
                        res[i] = [{"client_error": repr(e)}]
                ts.append(_th8.Thread(target=run))
            for t in ts:
                t.start()
            return ts, res

        def _clean_frac(res):
            ok = sum(1 for lines in res
                     if lines and lines[-1].get("done")
                     and not lines[-1].get("error"))
            return ok / max(len(res), 1)

        ts, pre = _rt_burst(6, "pre")
        for t in ts:
            t.join(timeout=300)
        _pre_goodput = _clean_frac(pre)

        ts, mid = _rt_burst(6, "kill")
        while not any(r and any(ln.get("tokens") for ln in r)
                      for r in mid if r is not None):
            time.sleep(0.005)
        _victim.send_signal(_sig8.SIGKILL)
        _kill_t = time.time()
        for t in ts:
            t.join(timeout=300)
        # Exactly one terminal outcome each — a dropped socket here is
        # a contract breach, not a benchmark data point.
        _no_terminal = sum(
            1 for lines in mid
            if not lines or "client_error" in lines[-1]
            or sum(1 for ln in lines if ln.get("done")) != 1)

        ts, post = _rt_burst(6, "post")
        for t in ts:
            t.join(timeout=300)
        _rec_goodput = _clean_frac(post)
        out.update({
            "fleet_chaos_goodput_frac": round(
                0.0 if _no_terminal else
                _rec_goodput / max(_pre_goodput, 1e-9), 3),
            "fleet_chaos_dip_goodput_frac": round(_clean_frac(mid), 3),
            "fleet_chaos_recovery_window_ms": round(
                (time.time() - _kill_t) * 1e3, 1),
            "fleet_chaos_missing_terminals": _no_terminal,
        })
        if _victim.poll() is None:
            _victim.kill()
        _victim.stdout.close()
    finally:
        if _rt is not None:
            _rt.stop()
        _rt_a.stop()
        _rt_b.stop()
except Exception as e:  # noqa: BLE001
    out["fleet_router_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Speculative decoding (VERDICT r3 item 5): committed-tokens/s for int8
# SELF-speculation — the target's own int8 copy drafts gamma tokens, the
# bf16 target verifies the chunk in one weight stream. The only reason
# speculative.py exists is wall-clock speedup; this measures it against
# the plain bf16 generate above (decode_tokens_per_sec). Acceptance
# telemetry rides along: with random-init weights the int8 shadow's
# argmax agreement is the worst case a real checkpoint would beat, so
# mean_committed contextualizes whatever speedup appears.
try:
    from tpu_bootstrap.workload.speculative import speculative_generate

    def timed_spec(steps, gamma):
        t0 = time.time()
        toks, stats = speculative_generate(
            dparams, qparams, dprompt, dcfg, dcfg, steps, gamma=gamma,
            with_stats=True)
        int(toks[0, -1])
        return time.time() - t0, stats

    g = 4
    timed_spec(d1, g)  # compile + warm both chunk shapes
    timed_spec(d2, g)
    samples, committed = [], []
    for _ in range(3):
        t1, s1 = timed_spec(d1, g)
        t2, s2 = timed_spec(d2, g)
        samples.append(max((t2 - t1) / (d2 - d1), 1e-9))
        committed += [float(s1["mean_committed"]), float(s2["mean_committed"])]
    sstep_s = sorted(samples)[len(samples) // 2]
    out.update({
        "speculative_tokens_per_sec": round(dbatch / sstep_s, 1),
        "speculative_speedup": round(step_s / sstep_s, 3),
        "speculative_gamma": g,
        # Averaged over the SAME runs the throughput median came from.
        "speculative_mean_committed": round(sum(committed) / len(committed), 2),
    })
except Exception as e:  # noqa: BLE001
    out["speculative_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Long-context DECODE: per-step cost against a fixed 4096-slot cache —
# the regime where the cache, not the weights, is the step's dominant
# HBM read (bf16 cache ~1 GB at batch 8 vs 268 MB of weights). Compares
# the bf16 einsum baseline against the full int8 serving stack: int8
# weights + int8 KV cache streamed by the Pallas decode-attention
# kernel. Uses prefill + a fixed-length scan of decode_steps directly
# (generate sizes its cache to prompt+steps, which would change L
# between measurements).
try:
    from tpu_bootstrap.workload.decode import decode_step, init_cache, prefill

    DL = 4096
    dlb = 8

    # params is an EXPLICIT jit argument, not a closure: closed-over
    # concrete arrays lower as HLO literal constants, and 268 MB of
    # baked-in weights overflows the tunnel's remote-compile request
    # body (HTTP 413 — bisected on hardware this round).
    @jax.jit
    def longctx_run(params, tok, caches):
        def body(carry, i):
            tok, caches = carry
            logits, caches = decode_step(params, tok, 64 + i, caches, dcfg)
            return (jnp.argmax(logits, -1).astype(tok.dtype), caches), ()
        (tok, caches), _ = lax.scan(body, (tok, caches), jnp.arange(64))
        return tok

    def longctx_step_ms(params, quantized):
        caches = init_cache(dcfg, dlb, DL, quantized=quantized)
        _, caches = prefill(params, dprompt, caches, dcfg)
        tok0 = dprompt[:, -1]
        int(longctx_run(params, tok0, caches)[0])  # compile + warm
        t0 = time.time()
        int(longctx_run(params, tok0, caches)[0])
        return (time.time() - t0) / 64 * 1e3

    base_ms = longctx_step_ms(dparams, quantized=False)
    q_ms = longctx_step_ms(qparams, quantized=True)
    out.update({
        "decode_L%d_step_ms" % DL: round(base_ms, 3),
        "decode_L%d_tokens_per_sec" % DL: round(dlb / (base_ms / 1e3), 1),
        "decode_L%d_int8kv_kernel_step_ms" % DL: round(q_ms, 3),
        "decode_L%d_int8kv_kernel_speedup" % DL: round(base_ms / q_ms, 3),
    })
except Exception as e:  # noqa: BLE001
    out["decode_longctx_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

# Long-context training on one chip: the same 134M model at seq 8192
# with the flash kernel and rematerialization — a configuration the
# dense path cannot touch (the seq^2 score tensors would blow HBM).
# The grid-streamed kernel formulation is what makes this compile: the
# earlier whole-slab kernels crashed the tunnel's remote compile helper
# when fused into full train graphs past ~6k seq. 16k seq at batch 1
# works too (25.7% MFU measured); 8192 is the benched point.
try:
    LSEQ = 8192
    lcfg = TrainConfig(
        model=ModelConfig(vocab_size=32768, num_layers=8, num_heads=16, head_dim=64,
                          embed_dim=1024, mlp_dim=4096, max_seq_len=LSEQ,
                          compute_dtype=jnp.bfloat16),
        mesh=MeshConfig(), attention="flash", remat=True,
    )
    lmesh = build_mesh(lcfg.mesh, jax.devices()[:1])
    lparams, lopt, lp_sh = init_train_state(lcfg, lmesh, jax.random.PRNGKey(0))
    lbatch = 2
    ltokens = jax.random.randint(jax.random.PRNGKey(1), (lbatch, LSEQ), 0, 32768)
    # AOT-compile ONCE and reuse the executable for both the timing loop
    # and the memory accounting — a second lower().compile() at seq 8192
    # through the tunnel would eat minutes of the timeout budget.
    lstep = make_train_step(lcfg, lmesh, lp_sh).lower(
        lparams, lopt, ltokens).compile()
    try:
        lmem = lstep.memory_analysis()
    except Exception:  # noqa: BLE001
        lmem = None
    lparams, lopt, ll = lstep(lparams, lopt, ltokens); float(ll)
    t0 = time.time()
    for _ in range(5):
        lparams, lopt, ll = lstep(lparams, lopt, ltokens)
    float(ll)
    lms = (time.time() - t0) / 5 * 1e3
    ln = sum(x.size for x in jax.tree.leaves(lparams))
    lm = lcfg.model
    ltoks = lbatch * (LSEQ - 1)
    lattn = 12 * lbatch * lm.num_layers * lm.num_heads * (LSEQ - 1) ** 2 * lm.head_dim
    out.update({
        "train_seq%d_step_ms" % LSEQ: round(lms, 3),
        "train_seq%d_tokens_per_sec" % LSEQ: round(ltoks / (lms / 1e3), 1),
        "train_seq%d_mfu_pct" % LSEQ: round(
            100 * (6 * ln * ltoks + lattn) / (lms / 1e3) / PEAK_BF16, 2),
    })
    emit()

    # Same configuration with the chunked cross-entropy head
    # (workload/xent.py): the (B, S, V) logits — 2 GB of f32 at this
    # shape — never materialize, so the step sheds its largest tensor and
    # the HBM traffic that came with it. The dense run's state (params +
    # Adam moments, ~1.6 GB f32) is dead now — drop it before the second
    # init so peak HBM holds one train state, not two.
    del lparams, lopt, lstep
    ccfg = TrainConfig(
        model=ModelConfig(vocab_size=32768, num_layers=8, num_heads=16, head_dim=64,
                          embed_dim=1024, mlp_dim=4096, max_seq_len=LSEQ,
                          compute_dtype=jnp.bfloat16, vocab_chunk=4096),
        mesh=MeshConfig(), attention="flash", remat=True,
    )
    cparams, copt, cp_sh = init_train_state(ccfg, lmesh, jax.random.PRNGKey(0))
    cstep = make_train_step(ccfg, lmesh, cp_sh).lower(
        cparams, copt, ltokens).compile()  # one compile: timing + memory
    cparams, copt, cl = cstep(cparams, copt, ltokens); float(cl)
    t0 = time.time()
    for _ in range(5):
        cparams, copt, cl = cstep(cparams, copt, ltokens)
    float(cl)
    cms = (time.time() - t0) / 5 * 1e3
    out.update({
        "train_seq%d_chunked_xent_step_ms" % LSEQ: round(cms, 3),
        "train_seq%d_chunked_xent_mfu_pct" % LSEQ: round(
            100 * (6 * ln * ltoks + lattn) / (cms / 1e3) / PEAK_BF16, 2),
        # Step-time parity is EXPECTED at this shape: attention FLOPs
        # (~1.7e13) dwarf the head's (~3e12) at seq 8192, so the head is
        # <15% of the step. The chunked head's real win is MEMORY — the
        # (B, S, V) f32 logits (2.1 GB here) never materialize — which
        # the compiler's own temp accounting shows below; it buys batch
        # (or seq) headroom, not step time.
        "chunked_xent_speedup_seq%d" % LSEQ: round(lms / cms, 3),
    })
    try:
        cmem = cstep.memory_analysis()
        out.update({
            "chunked_xent_temp_mb": round(cmem.temp_size_in_bytes / 1e6, 1),
            "dense_xent_temp_mb": round(lmem.temp_size_in_bytes / 1e6, 1),
            "chunked_xent_temp_reduction": round(
                lmem.temp_size_in_bytes / max(cmem.temp_size_in_bytes, 1), 2),
        })
    except Exception:  # noqa: BLE001
        pass  # memory_analysis availability varies by backend
    del cparams, copt, cstep  # drop the train state before interpreter exit
except Exception as e:  # noqa: BLE001
    out["longctx_bench_error"] = f"{type(e).__name__}: {e}"[:400]
emit()

"""


def _last_json_line(text: str):
    for ln in reversed(text.splitlines()):
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
    return None


# Last good full workload measurement (committed): the tunneled chip is
# single-tenant and can be held elsewhere for hours (round 1 lost its
# whole TPU half to this). When the live bench can't claim the chip, the
# cached numbers ride along under cached_* keys with their measurement
# time AND git commit — clearly labeled, never mixed with live keys, and
# flagged stale when the cache predates the current tree (round 2 shipped
# "measured on this build" numbers that actually predated four commits).
WORKLOAD_CACHE = REPO / ".workload_last_good.json"


def _git_fingerprint() -> str:
    """Current commit (short); uncommitted changes append a digest of the
    tracked-file diff, so two different dirty states of the same HEAD do
    NOT collide (a bare -dirty suffix would mark a cache measured on one
    uncommitted kernel as fresh for a different uncommitted kernel).
    'unknown' outside a git tree."""
    import hashlib

    try:
        head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              capture_output=True, text=True, cwd=REPO,
                              timeout=10).stdout.strip()
        if not head:
            return "unknown"
        # PROGRESS.jsonl is driver telemetry appended continuously, and
        # .workload_last_good.json is the cache THIS fingerprint guards
        # (writing it would otherwise dirty the tree and self-invalidate
        # the cache just written) — neither is a build input.
        diff = subprocess.run(
            ["git", "diff", "HEAD", "--", ".",
             ":!PROGRESS.jsonl", ":!.workload_last_good.json"],
            capture_output=True, text=True, cwd=REPO, timeout=10).stdout
        if diff:
            head += "-dirty-" + hashlib.sha256(diff.encode()).hexdigest()[:8]
        return head
    except Exception:  # noqa: BLE001
        return "unknown"


def _cache_workload(parsed: dict) -> None:
    """Cache chip-measured numbers for rounds when the tunnel is down.
    Partial runs (timeout after some sections) cache too, MERGED over the
    previous cache's results: keys a truncated run never reached keep
    their older measurement rather than vanishing — each key is the
    freshest value ever measured, with per-key fingerprints recording
    the tree that measured each. A COMPLETE clean run (every section
    succeeded) replaces the cache instead of merging, so renamed or
    removed metrics do not haunt the staleness flag forever."""
    if not parsed.get("chip_alive"):
        return
    complete = not any(k.endswith("_error") or k == "workload_bench_error"
                       for k in parsed)
    fresh = {k: v for k, v in parsed.items()
             if k != "workload_bench_error" and not k.endswith("_error")}
    head = _git_fingerprint()
    try:
        old, key_commits = {}, {}
        if not complete:
            try:
                cache = json.loads(WORKLOAD_CACHE.read_text())
                old = cache.get("results", {})
                # Per-key provenance: carried-over keys keep the
                # fingerprint of the run that actually measured them
                # (legacy caches without the map get the cache-level
                # commit for all keys).
                key_commits = cache.get("key_commits") or {
                    k: cache.get("commit", "unknown") for k in old}
            except (OSError, ValueError):
                pass
        key_commits.update({k: head for k in fresh})
        key_commits = {k: c for k, c in key_commits.items()
                       if k in old or k in fresh}
        WORKLOAD_CACHE.write_text(json.dumps(
            {"measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "commit": head,
             "key_commits": key_commits,
             "results": {**old, **fresh}}))
    except OSError:
        pass


# Direction-aware regression guard (VERDICT r4 item 4): between the r3
# and r4 caches, flash-seq2048 and MFU silently regressed and nobody
# could say when — the bench now self-reports any live key that moved
# >15% the wrong way against the previous cache, instead of needing a
# judge to diff rounds. Matched by suffix; keys that match neither
# family (booleans, configuration echoes like speculative_gamma) are
# not judged.
_HIGHER_BETTER = ("per_sec", "speedup", "mfu", "gbps",
                  "roofline_frac", "mean_committed", "committed_per_stream",
                  "slot_utilization", "temp_reduction", "agreement_pct",
                  "hit_rate", "admit_ratio", "accept_rate", "goodput_frac",
                  "busy_frac", "uplift", "slo_attainment",
                  "route_hit_frac")
# "_ms" must stay an endswith match (as a substring it would grab
# unrelated keys); the rest are distinctive enough to match anywhere —
# quality deltas carry format suffixes (quant_xent_delta_int8).
_LOWER_BETTER_SUFFIX = ("_ms",)
# preempt_total: at FIXED workload and pool size (the bench burst), a
# preemption-count climb means the expected-footprint estimate or the
# victim policy degraded into thrash — queue-wait and TTFT keys pay it.
_LOWER_BETTER_ANYWHERE = ("bytes_per_token", "xent_delta", "ppl_delta",
                          "temp_mb", "kv_blocks_peak_frac",
                          "preempt_total", "device_ms_per_token")
# Excluded despite a matching suffix: pure tunnel/backend noise.
_REGRESSION_EXEMPT = ("backend_init_s",)


def _flag_regressions(parsed: dict, prev_results: dict,
                      threshold: float = 0.15) -> None:
    """Annotate ``parsed`` (in place) with workload_regressions /
    workload_regression_count comparing each freshly measured numeric key
    against the previous cache. Runs AFTER the cache is rewritten so the
    flags never persist into it — each round is judged against the round
    before, not against its own output."""
    regressions = {}
    for key, now in sorted(parsed.items()):
        if key in _REGRESSION_EXEMPT or key.endswith("_error"):
            continue
        prev = prev_results.get(key)
        if (isinstance(now, bool) or isinstance(prev, bool)
                or not isinstance(now, (int, float))
                or not isinstance(prev, (int, float))):
            continue
        # Sign-robust margin: a plain multiplicative threshold misreads
        # signed metrics (prev = now = -0.02 would flag, since
        # -0.02 > -0.023) and flags meaningless near-zero jitter. The
        # wrong-way move must clear BOTH a relative margin on the
        # metric's magnitude and a small absolute floor.
        scale = max(abs(prev), abs(now))
        if any(s in key for s in _HIGHER_BETTER):
            move = prev - now  # positive = got worse
        elif (any(key.endswith(s) for s in _LOWER_BETTER_SUFFIX)
              or any(s in key for s in _LOWER_BETTER_ANYWHERE)):
            move = now - prev
        else:
            continue
        bad = move > threshold * scale and move > 1e-3
        if bad:
            regressions[key] = {"prev": prev, "now": now}
    if regressions:
        parsed["workload_regression_count"] = len(regressions)
        parsed["workload_regressions"] = dict(list(regressions.items())[:20])


def sim_bench() -> dict:
    """The digital-twin gate triple: one pinned deterministic scenario
    (``tools.sim``, virtual clock, CPU-pure, ~1s wall) driving the REAL
    router/autoscaler/SLO objects. The three keys are exact for a fixed
    seed, so any movement is a behavior change in the policy code the
    twin observes — which is precisely what the gate exists to catch."""
    try:
        from tools.sim import SimSpec
        from tools.sim import run as sim_run
        spec = SimSpec(scenario="diurnal", replicas=128, seed=1702)
        report, violations, _sim = sim_run(spec)
        return {
            "sim_scenario": spec.seed_str(),
            "sim_slo_attainment": report["slo_attainment"],
            "sim_goodput_frac": report["goodput_frac"],
            "sim_route_hit_frac": report["route_hit_frac"],
            "sim_violations": len(violations),
        }
    except Exception as e:  # noqa: BLE001 - the twin must not sink the bench
        return {"sim_bench_error": str(e)[:200]}


def _finish_workload(parsed: dict) -> dict:
    """Cache the fresh results, then judge them against the cache they
    replaced."""
    prev = {}
    try:
        prev = json.loads(WORKLOAD_CACHE.read_text()).get("results", {})
    except (OSError, json.JSONDecodeError):
        pass
    # The digital-twin triple rides the workload cache (it needs the
    # same per-key commit provenance and --check judgment), but is
    # measured here in the parent — it is chip-independent.
    parsed.update(sim_bench())
    _cache_workload(parsed)
    _flag_regressions(parsed, prev)
    return parsed


def _attach_cached_workload(err_result: dict) -> dict:
    try:
        cache = json.loads(WORKLOAD_CACHE.read_text())
    except (OSError, json.JSONDecodeError):
        return err_result
    commit = cache.get("commit", "unknown")
    head = _git_fingerprint()
    key_commits = cache.get("key_commits") or {
        k: commit for k in cache.get("results", {})}
    err_result["workload_cached_note"] = (
        "chip unavailable at bench time; cached_* keys were measured at "
        f"commit {commit} ({cache.get('measured_at', '?')})")
    # Per-key honesty: a MERGED cache can hold keys measured at several
    # commits (partial runs contribute only the sections they reached),
    # so staleness is judged per key, not from the cache-level stamp.
    stale = sorted(k for k, c in key_commits.items() if c != head)
    if stale:
        err_result["workload_cache_stale"] = True
        err_result["workload_cache_stale_keys"] = stale[:20]
        err_result["workload_cached_note"] += (
            f" — STALE: current tree is {head}; {len(stale)} cached keys "
            "were measured on a different build and may be unproven on "
            "the chip")
    for k, v in cache.get("results", {}).items():
        err_result[f"cached_{k}"] = v
    return err_result


def check_results(results: dict | None = None, threshold: float = 0.15):
    """--check: the regression GATE (vs the merely-informational flags
    the normal bench run annotates). Compares live numeric keys against
    .workload_last_good.json with the same direction-aware >15% rule and
    exits nonzero when a roofline-bandwidth key (``*_hbm_roofline_frac``
    / ``*_achieved_gbps`` — the kernel-efficiency contract this repo
    optimizes for), a paged-serving SLO key
    (``serve_paged_tokens_per_sec`` / ``serve_ttft_p99_ms``), or a
    prefix-cache SLO key (``serve_prefix_hit_rate`` /
    ``serve_cached_ttft_p50_ms`` — the sharing win must not silently
    erode) regressed; other regressions are loudly flagged but do not
    fail. ``results`` may be a pre-measured bench JSON (offline
    gating, tests); None runs the workload bench now. With no chip
    attached there are no live keys to judge — exits 0 with a note
    (staleness flagging alone is the old behavior this supersedes)."""
    try:
        cache = json.loads(WORKLOAD_CACHE.read_text())
        prev = cache.get("results", {})
    except (OSError, json.JSONDecodeError):
        print(json.dumps({"check_note": "no last-good cache; nothing to "
                                        "gate against", "check_failed": 0}))
        return 0
    # Baseline provenance, surfaced LOUDLY (the standing bench-cache
    # hygiene item): the gate compares against whatever
    # .workload_last_good.json holds, and a baseline measured on an
    # older kernel stack silently turns the comparison into fiction
    # (the stale 0.253 int8 roofline lesson). Age + per-key commit
    # provenance go in the summary; a stale baseline WARNS on stderr —
    # it does not fail, because a fresh on-chip run is exactly how the
    # cache gets replaced.
    head = _git_fingerprint()
    key_commits = cache.get("key_commits") or {
        k: cache.get("commit", "unknown") for k in prev}
    stale_keys = sorted(k for k, c in key_commits.items() if c != head)
    cache_age_days = None
    try:
        measured = time.mktime(time.strptime(
            cache.get("measured_at", ""), "%Y-%m-%dT%H:%M:%SZ"))
        cache_age_days = round((time.mktime(time.gmtime()) - measured)
                               / 86400, 1)
    except (ValueError, OverflowError):
        pass
    if stale_keys:
        print(f"WARNING: --check baseline {WORKLOAD_CACHE.name} predates "
              f"the current tree ({len(stale_keys)}/{len(key_commits)} "
              f"cached keys measured at other commits, e.g. "
              f"{stale_keys[0]} @ {key_commits[stale_keys[0]]}; cache "
              f"commit {cache.get('commit', 'unknown')}, measured "
              f"{cache.get('measured_at', '?')}"
              + (f", {cache_age_days} days ago" if cache_age_days is not None
                 else "")
              + ") — regressions below are judged against numbers the "
              "current kernel stack never produced; refresh the cache "
              "with an on-chip run", file=sys.stderr)
    if results is None:
        results = workload_bench()
    live = {k: v for k, v in results.items() if not k.startswith("cached_")}
    _flag_regressions(live, prev, threshold)
    regressions = live.get("workload_regressions", {})
    # Hard-failure families: the kernel-bandwidth contract, the paged
    # serving SLO pair (throughput and burst TTFT p99 — the two numbers
    # the paged engine ships to improve), the prefix-cache pair
    # (hit rate on the shared-prompt shape and warm-request TTFT p50 —
    # the two numbers the cache ships to improve), the overcommit
    # scheduler's admitted-ratio (expected-footprint admission must
    # keep beating refusal admission at equal KV memory), and the chaos
    # goodput fraction (recovery must keep completing within SLO under
    # the pinned fault schedule).
    # ... plus the fleet plane's pair: the cache digest must keep
    # ranking a warm replica above a cold one (uplift in blocks), and
    # the aggregator's scrape-staleness tail must not grow — a stale
    # /fleetz pane silently lies to the router/autoscaler reading it.
    # ... plus the attribution plane's triple: engine busy fraction,
    # MFU, and attributed device-ms per generated token on the fixed
    # tight burst — the ledger drifting idle-heavy, flops-poor, or
    # expensive-per-token is exactly the "who is eating my TPU"
    # regression this plane exists to catch.
    # ... plus the host-tier pair: the long-tail host hit rate (the
    # capacity the DRAM tier returns once HBM evicts) and — via the
    # speedup ratio — swap-restore staying cheaper than the
    # evict-and-recompute it replaces, the inequality the per-victim
    # cost model is premised on.
    # ... plus the fleet-router pair: cache-aware placement must keep
    # beating round-robin on served cached tokens (the router's reason
    # to exist), and the kill-a-replica recovery goodput must stay at
    # pre-kill levels — a silent drop in either means failover or
    # placement quietly broke.
    # ... plus the digital-twin triple: the pinned tools.sim scenario's
    # SLO attainment, goodput fraction, and placement hit rate. Exact
    # for a fixed seed, so ANY wrong-way move is a real behavior change
    # in the router/autoscaler/SLO code the twin drives.
    _HARD_KEYS = ("serve_paged_tokens_per_sec", "serve_ttft_p99_ms",
                  "serve_prefix_hit_rate", "serve_cached_ttft_p50_ms",
                  "serve_host_hit_rate", "serve_swap_restore_speedup",
                  "serve_admit_ratio", "serve_chaos_goodput_frac",
                  "fleet_digest_match_uplift",
                  "fleet_scrape_staleness_p99_ms",
                  "fleet_route_hit_uplift", "fleet_chaos_goodput_frac",
                  "serve_engine_busy_frac", "serve_mfu",
                  "serve_device_ms_per_token",
                  "sim_slo_attainment", "sim_goodput_frac",
                  "sim_route_hit_frac")
    hard = {k: v for k, v in regressions.items()
            if "hbm_roofline_frac" in k or "achieved_gbps" in k
            or k in _HARD_KEYS}
    judged = sum(1 for k, v in live.items()
                 if isinstance(v, (int, float)) and not isinstance(v, bool)
                 and k in prev)
    summary = {
        "check_threshold": threshold,
        "check_keys_judged": judged,
        "check_regressions": regressions,
        "check_hard_failures": hard,
        "check_failed": len(hard),
        # Baseline provenance: what the gate judged against, and how
        # trustworthy that baseline is for THIS tree.
        "check_cache_commit": cache.get("commit", "unknown"),
        "check_cache_measured_at": cache.get("measured_at", "?"),
        "check_cache_age_days": cache_age_days,
        "check_cache_stale_key_count": len(stale_keys),
    }
    # Hardware-assumption provenance: the peaks every roofline/MFU key
    # in this judgment was priced against, plus whether a chip was even
    # attached. A gate verdict is only as honest as its denominators —
    # a baseline measured under different peaks is the same fiction as
    # one measured at another commit, so the peaks ride the summary.
    from tpu_bootstrap import telemetry as _prov_tel
    summary.update({
        "check_peak_tflops": _prov_tel.peak_tflops(),
        "check_hbm_gbps": _prov_tel.hbm_peak_gbps(),
        "check_host_xfer_gbps": _prov_tel.host_xfer_gbps(),
        "check_chip_attached": bool(live.get("chip_alive")),
    })
    if stale_keys:
        summary["check_cache_stale_keys"] = stale_keys[:10]
    if judged == 0:
        summary["check_note"] = ("no live numeric keys overlap the cache "
                                 "(chip unavailable?); nothing gated")
    print(json.dumps(summary))
    return 1 if hard else 0


def workload_bench(timeout_secs: int | None = None):
    """Run the TPU workload micro-bench in a subprocess, first and
    isolated (VERDICT r1 item 1): explicit JAX_PLATFORMS passthrough and
    a hard timeout. Fast failures (crash, no JSON) get one retry; a
    timeout with ZERO output — hung backend init, i.e. a dead tunnel —
    does NOT retry (it would hang just as long again). The 1700s default
    cap (TPUBC_WORKLOAD_TIMEOUT overrides; hack/tpu-probe-loop.sh's
    fallback must track it): a fully cold run through the tunnel
    measured ~900s through the speculative section (20+ Mosaic
    compiles), the round-3 900s cap cost that run its long-context
    sections, and the round-5 sections (trained-model quality,
    continuous batching) add ~20 fresh cold compiles over the 1400s
    r4 budget; a timeout loses the tail, whose numbers ride the merged
    cache.
    The subprocess emits its accumulated results after every milestone,
    so even a timeout or crash returns whatever was measured up to that
    point — and those partials are cached (merged) too. On total failure
    returns the error string instead of raising — the control-plane
    metric is the primary and must never be lost to a workload
    hiccup."""
    if timeout_secs is None:
        # 1700s: the r5 sections (trained-model quality, continuous
        # batching) add ~20 fresh compiles on a cold tunnel cache; 1400s
        # covered the r4 section set.
        timeout_secs = int(os.environ.get("TPUBC_WORKLOAD_TIMEOUT", "1700"))
    # Fail-FAST on a dead tunnel: a healthy backend prints its first
    # milestone (workload_backend/chip_alive) within seconds-to-a-couple-
    # minutes; a held/dead tunnel hangs in backend init with ZERO output.
    # Waiting the full cap in silence would burn the driver's bench
    # budget before the control-plane sections ever run (the workload
    # goes first), so silence past the init window kills the attempt.
    init_secs = int(os.environ.get("TPUBC_WORKLOAD_INIT_TIMEOUT", "420"))
    import threading

    def _reader(stream, sink, flag):
        for ln in iter(stream.readline, b""):
            sink.append(ln.decode(errors="replace"))
            flag.set()

    err = ""
    for _attempt in range(2):
        out_chunks: list = []
        err_chunks: list = []
        got_output = threading.Event()
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", WORKLOAD_BENCH_SCRIPT],
            env={**os.environ, "TPUBC_REPO": str(REPO)},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=str(REPO),
        )
        # BOTH pipes get reader threads: an undrained stderr (JAX/Mosaic
        # compile warnings easily exceed the ~64KB pipe buffer) would
        # block the child mid-run and masquerade as a timeout.
        readers = [
            threading.Thread(target=_reader,
                             args=(proc.stdout, out_chunks, got_output),
                             daemon=True),
            threading.Thread(target=_reader,
                             args=(proc.stderr, err_chunks, threading.Event()),
                             daemon=True),
        ]
        for t in readers:
            t.start()
        # One deadline for the WHOLE attempt, from spawn — the init
        # window must not extend it.
        deadline = time.monotonic() + timeout_secs
        init_deadline = time.monotonic() + min(init_secs, timeout_secs)
        try:
            # Init window: wake on first output OR child exit (a fast
            # crash must fall through to the retrying crash path in
            # milliseconds, not sit out the window).
            while (not got_output.is_set() and proc.poll() is None
                   and time.monotonic() < init_deadline):
                got_output.wait(timeout=0.25)
            if not got_output.is_set() and proc.poll() is None:
                # A retry would hang just as long — don't burn another
                # window; the control-plane bench is waiting.
                return _attach_cached_workload(
                    {"workload_bench_error":
                     f"no output after {init_secs}s (backend init hang — "
                     "tunnel down?); failed fast to protect the "
                     "control-plane budget"})
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                for t in readers:
                    t.join(timeout=5)
                parsed = _last_json_line("".join(out_chunks))
                if parsed is not None:
                    # Error key BEFORE caching: _cache_workload decides
                    # merge-vs-replace by the presence of error keys, and
                    # a truncated run cached as "complete" would REPLACE
                    # the cache and drop every carried-over key.
                    parsed.setdefault(
                        "workload_bench_error",
                        f"timed out after {timeout_secs}s with partial results")
                    _finish_workload(parsed)
                    return parsed
                err = f"timed out after {timeout_secs}s, unparseable output"
                continue
            for t in readers:
                t.join(timeout=5)
            stdout = "".join(out_chunks)
            if proc.returncode == 0:
                parsed = _last_json_line(stdout)
                if parsed is not None:
                    _finish_workload(parsed)
                    return parsed
                err = "no JSON output: " + stdout[-200:]
            else:
                # Crash after partial progress: keep the measured numbers,
                # annotate the crash. Retry only if nothing was measured.
                parsed = _last_json_line(stdout)
                tail = "".join(err_chunks)[-400:]
                if parsed is not None:
                    # Same ordering as the timeout path: the error key
                    # must precede caching to keep the merge behavior.
                    parsed.setdefault("workload_bench_error",
                                      f"exited {proc.returncode}: {tail}")
                    _finish_workload(parsed)
                    return parsed
                err = tail or f"exited {proc.returncode} with no output"
        except Exception as e:  # noqa: BLE001
            err = str(e)[:400]
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait()  # never leave a zombie
            for stream in (proc.stdout, proc.stderr):
                try:
                    stream.close()
                except OSError:
                    pass
    return _attach_cached_workload({"workload_bench_error": err})


def admission_bench(n: int = 2000, threads: int = 4):
    """Mutating-webhook throughput: AdmissionReview POSTs/sec against the
    daemon over keep-alive HTTP (CONF_TLS_DISABLED — TLS termination is
    cert-manager-standardized and not the interesting axis), plus p50
    end-to-end latency. The reference serves this path from 2 axum
    replicas with a 10s timeout; per-request policy cost is the metric
    that bounds how hard the API server can hammer one replica."""
    import http.client
    import threading

    port = free_port()
    proc = subprocess.Popen(
        [str(REPO / "native" / "build" / "tpubc-admission")],
        env={
            **os.environ,
            "CONF_LISTEN_ADDR": "127.0.0.1",
            "CONF_LISTEN_PORT": str(port),
            "CONF_TLS_DISABLED": "1",
            "CONF_AUTHORIZED_GROUP_NAMES": "tpu,admin",
            "TPUBC_LOG": "error",
        },
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    review = json.dumps({
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": "bench",
            "operation": "CREATE",
            "userInfo": {"username": "oidc:alice", "groups": ["tpu"]},
            "object": {
                "apiVersion": "tpu.bacchus.io/v1",
                "kind": "UserBootstrap",
                "metadata": {"name": "alice"},
                "spec": {"tpu": {"accelerator": "tpu-v5p-slice", "topology": "4x4x4"}},
            },
        },
    }).encode()

    try:
        wait_health(port, proc)
        latencies: list[float] = []
        errors: list[str] = []
        lock = threading.Lock()

        def worker(count):
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
                local = []
                for _ in range(count):
                    t0 = time.time()
                    conn.request("POST", "/mutate", review,
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    body = resp.read()
                    assert resp.status == 200 and b'"allowed":true' in body.replace(b" ", b""), \
                        body[:200]
                    local.append((time.time() - t0) * 1000)
                conn.close()
                with lock:
                    latencies.extend(local)
            except Exception as e:  # noqa: BLE001
                # Surface worker failures instead of silently reporting a
                # throughput computed from the surviving subset.
                with lock:
                    errors.append(f"{type(e).__name__}: {e}"[:200])

        worker(50)  # warm
        if errors:
            return {"admission_bench_error": errors[0]}
        latencies.clear()
        t0 = time.time()
        ts = [threading.Thread(target=worker, args=(n // threads,)) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        elapsed = time.time() - t0
        if errors or any(t.is_alive() for t in ts):
            return {"admission_bench_error":
                    errors[0] if errors else "worker timed out after 120s"}
        latencies.sort()
        return {
            "admission_mutations_per_sec": round(len(latencies) / elapsed, 1),
            "admission_p50_ms": round(latencies[len(latencies) // 2], 3),
        }
    except Exception as e:  # noqa: BLE001
        return {"admission_bench_error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def webhook_path_bench(k: int = 30):
    """p50 CR-apply -> JobSet-created through the DEPLOYED write path
    (VERDICT r3 items 2/3, in-environment form): the real admission
    daemon registered as a MutatingWebhookConfiguration in the fake
    apiserver's write path over caBundle-verified TLS with
    failurePolicy=Fail, CRD schema validation after the patch, then the
    controller's reconcile. Each sample is the full onboarding
    lifecycle: impersonated CREATE (webhook mutate + validate +
    persist) -> sheet-gate status write -> JobSet visible."""
    import base64
    import ssl
    import tempfile
    import urllib.error

    fake = None
    procs = []
    try:
        tmp = Path(tempfile.mkdtemp())
        cert, keyf = tmp / "adm.crt", tmp / "adm.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(keyf), "-out", str(cert), "-days", "1",
             "-subj", "/CN=bench-admission"],
            check=True, capture_output=True)

        fake = FakeKube().start()
        aport, cport = free_port(), free_port()
        adm = subprocess.Popen(
            [str(REPO / "native" / "build" / "tpubc-admission")],
            env={**os.environ, "CONF_LISTEN_ADDR": "127.0.0.1",
                 "CONF_LISTEN_PORT": str(aport), "CONF_CERT_PATH": str(cert),
                 "CONF_KEY_PATH": str(keyf),
                 "CONF_AUTHORIZED_GROUP_NAMES": "tpu,admin",
                 "TPUBC_LOG": "error"},
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        procs.append(adm)
        ctrl = subprocess.Popen(
            [str(REPO / "native" / "build" / "tpubc-controller")],
            env={**os.environ, "CONF_KUBE_API_URL": fake.url,
                 "CONF_LISTEN_ADDR": "127.0.0.1",
                 "CONF_LISTEN_PORT": str(cport), "TPUBC_LOG": "error"},
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        procs.append(ctrl)
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        deadline = time.time() + 15
        while True:
            try:
                urllib.request.urlopen(f"https://127.0.0.1:{aport}/health",
                                       timeout=1, context=ctx)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError("admission TLS health timeout")
                time.sleep(0.05)
        wait_health(cport, ctrl)

        def post(path, body, headers=None):
            req = urllib.request.Request(
                fake.url + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json", **(headers or {})},
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                return json.loads(r.read())

        post("/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations", {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "tpubc-bench"},
            "webhooks": [{
                "name": "mutate.tpu.bacchus.io",
                "clientConfig": {
                    "url": f"https://127.0.0.1:{aport}/mutate",
                    "caBundle": base64.b64encode(cert.read_bytes()).decode(),
                },
                "rules": [{"apiGroups": ["tpu.bacchus.io"],
                           "apiVersions": ["v1"],
                           "resources": ["userbootstraps"],
                           "operations": ["CREATE", "UPDATE", "DELETE"]}],
                "failurePolicy": "Fail", "timeoutSeconds": 10,
            }],
        })

        latencies = []
        for i in range(k):
            name = f"wh{i:03d}"
            t0 = time.time()
            post("/apis/tpu.bacchus.io/v1/userbootstraps",
                 {"apiVersion": "tpu.bacchus.io/v1", "kind": "UserBootstrap",
                  "metadata": {"name": name},
                  "spec": {"tpu": {"accelerator": "tpu-v5-lite-podslice",
                                   "topology": "2x2"}}},
                 headers={"Impersonate-User": f"oidc:{name}",
                          "Impersonate-Group": "tpu"})
            req = urllib.request.Request(
                fake.url + f"/apis/tpu.bacchus.io/v1/userbootstraps/{name}/status",
                data=json.dumps({"status": {"synchronized_with_sheet": True}}).encode(),
                headers={"Content-Type": "application/merge-patch+json"},
                method="PATCH")
            urllib.request.urlopen(req, timeout=15)
            while True:
                with fake.store.lock:
                    if fake.store.objects.get(KEY_JS(name), {}).get(f"{name}-slice"):
                        break
                if time.time() - t0 > 30:
                    raise TimeoutError(f"{name} never produced a JobSet")
                time.sleep(0.002)
            latencies.append((time.time() - t0) * 1000)
        latencies.sort()
        return {
            "webhook_path_p50_apply_to_jobset_ms": round(
                latencies[len(latencies) // 2], 2),
            "webhook_path_p90_apply_to_jobset_ms": round(
                latencies[int(len(latencies) * 0.9)], 2),
            "webhook_path_samples": k,
        }
    except Exception as e:  # noqa: BLE001
        # Never take the control-plane metrics down with this section —
        # a missing binary or spawn failure becomes an error key.
        return {"webhook_path_bench_error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        if fake is not None:
            fake.stop()


# Small CPU workload run for the merged trace: a few real train steps and
# a decode under tpu_bootstrap.telemetry spans, rooted in the trace id the
# admission webhook stamped on the CR (passed via TPUBC_TRACE_ID exactly
# as the JobSet would inject it). Runs in a subprocess so the forced-CPU
# JAX config never leaks into the caller.
TRACE_WORKLOAD_SCRIPT = r"""
import os, sys
sys.path.insert(0, os.environ["TPUBC_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from tpu_bootstrap import telemetry
from tpu_bootstrap.workload.model import ModelConfig, init_params
from tpu_bootstrap.workload.train import TrainConfig, train_loop
from tpu_bootstrap.workload.decode import generate

cfg = TrainConfig(model=ModelConfig(vocab_size=256, num_layers=2, num_heads=2,
                                    head_dim=8, embed_dim=16, mlp_dim=32,
                                    max_seq_len=32))
with telemetry.span("workload.train", steps=3):
    train_loop(cfg, 3, log_every=0)
params = init_params(cfg.model, jax.random.PRNGKey(0))
prompt = jnp.zeros((2, 4), jnp.int32)
generate(params, prompt, cfg.model, 4)
# A tight paged serve run under the SAME propagated trace id: the
# merged timeline gains per-request span TREES (serve.request +
# serve.phase.{queue,prefill,decode,recompute} children, a preempted
# leg included) instead of one opaque bar per request.
os.environ["TPUBC_EXPECTED_NEW"] = "2"
from tpu_bootstrap.workload.serving import Request, serve
serve(params, cfg.model, [Request(rid=i, tokens=[1 + i, 2, 3], max_new=8)
                          for i in range(6)],
      6, paged=True, block_size=4, kv_blocks=8, prefill_budget=4)
telemetry.tracer().dump(os.environ["TPUBC_TRACE_FILE"])
print(len(telemetry.tracer().spans()))
"""


def trace_capture(out_path: str):
    """--trace-out: drive ONE UserBootstrap through the deployed write
    path (TLS webhook -> fake API server -> controller -> JobSet) with
    TPUBC_TRACE_FILE set on both daemons, run a small CPU workload under
    the same trace id, and merge all three Chrome traces into out_path.
    Prints one JSON summary line."""
    import base64
    import ssl
    import tempfile
    import urllib.error

    from tpu_bootstrap import telemetry
    from tpu_bootstrap.fakeapi import FakeKube

    tmp = Path(tempfile.mkdtemp())
    adm_trace, ctrl_trace, wl_trace = (tmp / "admission.json", tmp / "controller.json",
                                       tmp / "workload.json")
    cert, keyf = tmp / "adm.crt", tmp / "adm.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(keyf), "-out", str(cert), "-days", "1",
         "-subj", "/CN=trace-admission"],
        check=True, capture_output=True)

    fake = FakeKube().start()
    procs = []
    try:
        aport, cport = free_port(), free_port()
        procs.append(subprocess.Popen(
            [str(REPO / "native" / "build" / "tpubc-admission")],
            env={**os.environ, "CONF_LISTEN_ADDR": "127.0.0.1",
                 "CONF_LISTEN_PORT": str(aport), "CONF_CERT_PATH": str(cert),
                 "CONF_KEY_PATH": str(keyf),
                 "CONF_AUTHORIZED_GROUP_NAMES": "tpu,admin",
                 "TPUBC_TRACE_FILE": str(adm_trace), "TPUBC_LOG": "error"},
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE))
        procs.append(subprocess.Popen(
            [str(REPO / "native" / "build" / "tpubc-controller")],
            env={**os.environ, "CONF_KUBE_API_URL": fake.url,
                 "CONF_LISTEN_ADDR": "127.0.0.1",
                 "CONF_LISTEN_PORT": str(cport),
                 "TPUBC_TRACE_FILE": str(ctrl_trace), "TPUBC_LOG": "error"},
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE))
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        deadline = time.time() + 15
        while True:
            try:
                urllib.request.urlopen(f"https://127.0.0.1:{aport}/health",
                                       timeout=1, context=ctx)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError("admission TLS health timeout")
                time.sleep(0.05)
        wait_health(cport, procs[1])

        def post(path, body, headers=None):
            req = urllib.request.Request(
                fake.url + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json", **(headers or {})},
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                return json.loads(r.read())

        post("/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations", {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "tpubc-trace"},
            "webhooks": [{
                "name": "mutate.tpu.bacchus.io",
                "clientConfig": {
                    "url": f"https://127.0.0.1:{aport}/mutate",
                    "caBundle": base64.b64encode(cert.read_bytes()).decode(),
                },
                "rules": [{"apiGroups": ["tpu.bacchus.io"],
                           "apiVersions": ["v1"],
                           "resources": ["userbootstraps"],
                           "operations": ["CREATE", "UPDATE", "DELETE"]}],
                "failurePolicy": "Fail", "timeoutSeconds": 10,
            }],
        })
        name = "traced"
        post("/apis/tpu.bacchus.io/v1/userbootstraps",
             {"apiVersion": "tpu.bacchus.io/v1", "kind": "UserBootstrap",
              "metadata": {"name": name},
              "spec": {"tpu": {"accelerator": "tpu-v5-lite-podslice",
                               "topology": "2x2"}}},
             headers={"Impersonate-User": f"oidc:{name}",
                      "Impersonate-Group": "tpu"})
        req = urllib.request.Request(
            fake.url + f"/apis/tpu.bacchus.io/v1/userbootstraps/{name}/status",
            data=json.dumps({"status": {"synchronized_with_sheet": True}}).encode(),
            headers={"Content-Type": "application/merge-patch+json"},
            method="PATCH")
        urllib.request.urlopen(req, timeout=15)
        t0 = time.time()
        while True:
            with fake.store.lock:
                js = fake.store.objects.get(KEY_JS(name), {}).get(f"{name}-slice")
            if js:
                break
            if time.time() - t0 > 30:
                raise TimeoutError("traced CR never produced a JobSet")
            time.sleep(0.01)
        trace_id = js["metadata"]["annotations"].get(telemetry.TRACE_ANNOTATION, "")

        # Workload leg, rooted in the SAME trace id (the TPUBC_TRACE_ID
        # contract the JobSet env carries).
        wl = subprocess.run(
            [sys.executable, "-c", TRACE_WORKLOAD_SCRIPT],
            env={**os.environ, "TPUBC_REPO": str(REPO),
                 "JAX_PLATFORMS": "cpu",
                 "TPUBC_TRACE_ID": trace_id or "",
                 "TPUBC_TRACE_FILE": str(wl_trace)},
            capture_output=True, timeout=300)
        if wl.returncode != 0:
            raise RuntimeError("trace workload failed: "
                               + wl.stderr.decode()[-400:])
    finally:
        # SIGTERM -> graceful shutdown writes each daemon's trace file.
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        fake.stop()

    merged = telemetry.merge_chrome_traces(
        out_path, [str(adm_trace), str(ctrl_trace), str(wl_trace)])
    events = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    processes = sorted({e.get("cat", "?") for e in events})
    in_trace = [e for e in events
                if trace_id and e.get("args", {}).get("trace_id") == trace_id]
    bad = [e for e in events if e.get("dur", 0) < 0 or e.get("ts", 0) <= 0]
    summary = {
        "trace_out": str(out_path),
        "trace_id": trace_id,
        "span_count": len(events),
        "processes": processes,
        "spans_in_propagated_trace": len(in_trace),
        "negative_or_zero_timestamps": len(bad),
    }
    print(json.dumps(summary))
    return summary


FLEET_REPLICA_SCRIPT = r"""
import os, sys
sys.path.insert(0, os.environ["TPUBC_REPO"])
import jax
jax.config.update("jax_platforms",
                  os.environ.get("JAX_PLATFORMS", "cpu") or "cpu")
from tpu_bootstrap.workload.ingress import IngressServer
from tpu_bootstrap.workload.model import ModelConfig, init_params

cfg = ModelConfig(vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
                  embed_dim=16, mlp_dim=32, max_seq_len=64)
params = init_params(cfg, jax.random.PRNGKey(0))
IngressServer(params, cfg, port=int(sys.argv[1]), batch_size=2, paged=True,
              block_size=8, host="127.0.0.1").serve_forever()
"""


def fleet_trace_capture(out_path: str):
    """--trace-out --fleet: the cross-replica half of the trace story.
    Two SUBPROCESS serve replicas (separate tracer buffers — the stitch
    below is a real out-of-band join, not one process talking to
    itself) each serve one request under the SAME trace id; the fleetz
    aggregator scrapes both /traces.json buffers and writes the
    stitched Chrome timeline (one pid per replica, rows grouped by
    trace id) to out_path. Prints one JSON summary line."""
    from tpu_bootstrap.workload.fleetz import FleetAggregator, stitch_chrome

    ports = [free_port(), free_port()]
    procs = [subprocess.Popen(
        [sys.executable, "-c", FLEET_REPLICA_SCRIPT, str(p)],
        env={**os.environ, "TPUBC_REPO": str(REPO),
             "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS") or "cpu"},
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        for p in ports]
    trace_id = "f1ee7" + os.urandom(6).hex()
    try:
        for port, proc in zip(ports, procs):
            deadline = time.time() + 120
            while True:
                if proc.poll() is not None:
                    raise RuntimeError("fleet replica exited: "
                                       + proc.stderr.read().decode()[-2000:])
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1)
                    break
                except OSError:
                    if time.time() > deadline:
                        raise TimeoutError("fleet replica health timeout")
                    time.sleep(0.05)
        for port in ports:  # one request per replica, one shared trace id
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"tokens": [1, 2, 3], "max_new": 4,
                                 "stream": False,
                                 "trace_id": trace_id}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as r:
                assert json.loads(r.read())["done"]
        agg = FleetAggregator([f"127.0.0.1:{p}" for p in ports],
                              port=0, host="127.0.0.1", poll_s=0.1)
        try:
            agg.poll_once()
            doc = stitch_chrome(agg._trace_docs())
        finally:
            agg.httpd.server_close()
        Path(out_path).write_text(json.dumps(doc))
    finally:
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    shared = [e for e in events
              if e.get("args", {}).get("trace_id") == trace_id]
    summary = {
        "trace_out": str(out_path),
        "trace_id": trace_id,
        "replicas": len(ports),
        "span_count": len(events),
        "spans_in_shared_trace": len(shared),
        "pids_in_shared_trace": len({e["pid"] for e in shared}),
    }
    print(json.dumps(summary))
    return summary


def record_trace(out_path: str, n_requests: int = 24):
    """--record-trace: drive a short live burst through the real paged
    ingress and write its ``/requestz?format=jsonl`` arrival capture to
    ``out_path`` — the file ``python -m tools.sim --scenario replay
    --replay-trace PATH`` replays against the fleet digital twin. The
    burst mixes priorities, prompt lengths, and decode budgets so the
    capture exercises every field the replay loader reads."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from tpu_bootstrap.workload.ingress import IngressServer
    from tpu_bootstrap.workload.model import ModelConfig, init_params

    cfg = ModelConfig(vocab_size=128, num_layers=2, num_heads=2,
                      head_dim=8, embed_dim=16, mlp_dim=32,
                      max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ingress = IngressServer(params, cfg, port=0, batch_size=4,
                            paged=True, block_size=16).start()
    try:
        for i in range(n_requests):
            body = json.dumps({
                "tokens": [1 + (i % 7)] * (3 + (i % 4) * 5),
                "max_new": 4 + (i % 3) * 4, "stream": False,
                "priority": i % 2,
                "trace_id": f"rectrace{i:08x}"}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ingress.port}/v1/generate",
                data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as r:
                out = json.loads(r.read())
            assert out["done"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ingress.port}/requestz?format=jsonl",
                timeout=30) as r:
            data = r.read()
    finally:
        ingress.stop()
    Path(out_path).write_bytes(data)
    summary = {"record_trace": out_path,
               "records": data.count(b"\n"),
               "replay_with": f"python -m tools.sim --scenario replay "
                              f"--replay-trace {out_path}"}
    print(json.dumps(summary))
    return summary


def slo_report(out_path: str, n_crs: int = 30):
    """--slo-report: the operator-facing SLO summary for one bench
    trajectory. Two legs share one process:

    1. Serve leg: a small model behind the real ingress engine answers a
       handful of live HTTP generate calls — filling the workload
       registry's TTFT/latency histograms and qps/tokens-per-sec gauges.
    2. Control-plane leg: the registry is then exposed on a local
       metrics server standing in for worker 0, and the real controller
       (CONF_WORKLOAD_SCRAPE=1 pointed at it) converges n_crs CRs whose
       JobSets a simulator marks ready — driving phase to Running, the
       time-to-Running histogram, and the status.slice.workload merge.

    The emitted JSON answers: how fast do slices reach Running (p50/p99),
    how often do reconciles fail, what latency does serving deliver
    (TTFT, tokens/s), and does /statusz join it all by trace id.
    """
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from tpu_bootstrap import telemetry
    from tpu_bootstrap.fakeapi import FakeKube
    from tpu_bootstrap.workload.ingress import IngressServer
    from tpu_bootstrap.workload.model import ModelConfig, init_params

    # ---- serve leg --------------------------------------------------------
    # Paged engine: the leg also exercises the request-lifecycle flight
    # recorder (/requestz), the pool snapshot (/poolz), and — with the
    # alternating priorities below — the per-class SLO split.
    cfg = ModelConfig(vocab_size=128, num_layers=2, num_heads=2, head_dim=8,
                      embed_dim=16, mlp_dim=32, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ingress = IngressServer(params, cfg, port=0, batch_size=4,
                            paged=True, block_size=16).start()

    def generate_once(tokens, max_new, priority=0, trace_id=""):
        req = urllib.request.Request(
            f"http://127.0.0.1:{ingress.port}/v1/generate",
            data=json.dumps({"tokens": tokens, "max_new": max_new,
                             "stream": False, "priority": priority,
                             **({"trace_id": trace_id}
                                if trace_id else {})}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            return json.loads(r.read())

    def ingress_get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ingress.port}{path}", timeout=30) as r:
            return json.loads(r.read())

    n_serve = 8
    for i in range(n_serve):
        out = generate_once([1 + i, 2, 3], 4 + (i % 3) * 4,
                            priority=i % 2, trace_id=f"slobench{i:08x}")
        assert out["done"] and len(out["tokens"]) >= 4
        assert out.get("trace_id") == f"slobench{i:08x}"
        assert "timing" in out  # the phase-attributed response block
    requestz = ingress_get("/requestz")
    poolz = ingress_get("/poolz")
    ingress.stop()

    # Worker-0 stand-in: the SAME registry the serve leg just filled,
    # behind the same /metrics.json route a slice worker serves.
    worker_metrics = telemetry.start_metrics_server(0, host="127.0.0.1")
    worker_port = worker_metrics.server_address[1]

    # ---- control-plane leg ------------------------------------------------
    fake = FakeKube().start()
    port = free_port()
    proc = subprocess.Popen(
        [str(REPO / "native" / "build" / "tpubc-controller")],
        env={**os.environ,
             "CONF_KUBE_API_URL": fake.url,
             "CONF_LISTEN_ADDR": "127.0.0.1",
             "CONF_LISTEN_PORT": str(port),
             "CONF_WORKLOAD_SCRAPE": "1",
             "CONF_WORKLOAD_SCRAPE_ADDR": f"127.0.0.1:{worker_port}",
             "CONF_WORKLOAD_SCRAPE_INTERVAL_SECS": "1",
             "TPUBC_LOG": "error"},
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        wait_health(port, proc)

        # JobSet-readiness simulator: the moment a JobSet exists, mark its
        # gang ready (what the JobSet controller does on a real cluster) —
        # the controller's child watch then drives phase to Running.
        stop_sim = threading.Event()

        def simulate_ready():
            while not stop_sim.is_set():
                with fake.store.lock:
                    pending = [
                        (f"slo-{i:03d}", dict(js))
                        for i in range(n_crs)
                        for js in [fake.store.objects.get(
                            KEY_JS(f"slo-{i:03d}"), {}).get(f"slo-{i:03d}-slice")]
                        if js and not js.get("status")
                    ]
                for ns, js in pending:
                    js["status"] = {"replicatedJobsStatus": [
                        {"name": "workers", "ready": 1}]}
                    fake.store.upsert(KEY_JS(ns), f"{ns}-slice", js,
                                      preserve_status=False)
                time.sleep(0.01)

        sim = threading.Thread(target=simulate_ready, daemon=True)
        sim.start()

        t0 = time.time()
        for i in range(n_crs):
            fake.create_ub(f"slo-{i:03d}", spec=cr_spec(), status=dict(SYNCED))

        def phase(name):
            ub = fake.get(fake.KEY_UB, name) or {}
            return ub.get("status", {}).get("slice", {}).get("phase")

        deadline = time.time() + 120
        while time.time() < deadline:
            if all(phase(f"slo-{i:03d}") == "Running" for i in range(n_crs)):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("SLO CRs never all reached Running")
        running_elapsed = time.time() - t0

        # The scrape loop (1s interval) must merge the worker summary.
        sample = None
        deadline = time.time() + 30
        while time.time() < deadline:
            ub = fake.get(fake.KEY_UB, "slo-000") or {}
            sample = ub.get("status", {}).get("slice", {}).get("workload")
            if sample:
                break
            time.sleep(0.05)
        if not sample:
            raise TimeoutError("status.slice.workload never merged")
        stop_sim.set()

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=5) as r:
            m = json.loads(r.read())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz?name=slo-000",
                timeout=5) as r:
            statusz = json.loads(r.read())
        outcomes = statusz["objects"]["slo-000"]  # lint: allow(endpoint-ghost-read) — dynamic object name, not a schema key
        reconcile_outcomes = [o for o in outcomes if o["op"] == "reconcile"]
        serve_json = telemetry.metrics().to_json()

        reconciles = m.get("reconciles_total", 0)
        errors = m.get("reconcile_errors_total", 0)
        report = {
            "slo_report_version": 3,
            "bench_commit": _git_fingerprint(),
            "fakeapi_version": FAKEAPI_VERSION,
            "n_crs": n_crs,
            "all_running_elapsed_s": round(running_elapsed, 3),
            # Provisioning SLO: the controller's own first-seen->Running
            # condition-transition histogram.
            "time_to_running_p50_ms": m.get("tpubc_time_to_running_ms_p50"),
            "time_to_running_p99_ms": m.get("tpubc_time_to_running_ms_p99"),
            "time_to_running_count": m.get("tpubc_time_to_running_ms_count"),
            "reconciles_total": reconciles,
            "reconcile_errors_total": errors,
            "reconcile_error_rate": round(errors / max(reconciles, 1), 4),
            "reconcile_p50_ms": m.get("tpubc_reconcile_duration_ms_p50"),
            "workqueue_depth": m.get("workqueue_depth"),
            "watch_last_event_age_seconds": m.get("watch_last_event_age_seconds"),
            "workload_scrapes_total": m.get("workload_scrapes_total"),
            # Serving SLO, from the serve leg's registry.
            "serve_requests": serve_json.get("serve_requests_total"),
            "serve_ttft_p50_ms": serve_json.get("serve_ttft_ms_p50"),
            "serve_ttft_p99_ms": serve_json.get("serve_ttft_ms_p99"),
            "serve_request_p50_ms": serve_json.get("serve_request_ms_p50"),
            "serve_tokens_per_sec": serve_json.get("serve_tokens_per_sec"),
            "serve_qps": serve_json.get("serve_qps"),
            # Phase attribution: where the serve leg's request time
            # went (queue vs prefill vs decode vs recompute), the
            # per-priority-class TTFT split, and one /requestz record's
            # phase breakdown as evidence the flight recorder was live.
            "serve_phase_shares": {
                k: serve_json.get(f"serve_phase_share_{k}")
                for k in ("queue", "prefill", "decode", "recompute")},
            "serve_ttft_by_class_p50_ms": {
                c: serve_json.get(f'serve_ttft_ms{{priority="{c}"}}_p50')
                for c in ("0", "1")},
            "serve_queue_wait_by_class_p50_ms": {
                c: serve_json.get(
                    f'serve_queue_wait_ms{{priority="{c}"}}_p50')
                for c in ("0", "1")},
            # Device-time attribution: the busy/idle ledger's headline
            # gauges plus the per-class device-seconds split — "who is
            # eating my TPU", answered from the same serve leg.
            "serve_engine_busy_frac":
                serve_json.get("serve_engine_busy_frac"),
            "serve_mfu": serve_json.get("serve_mfu"),
            "serve_device_ms_by_class": {
                c: serve_json.get(f'serve_device_ms_total{{priority="{c}"}}')
                for c in ("0", "1")},
            "requestz_requests": len(requestz["requests"]),
            "requestz_sample": ({
                "rid": requestz["requests"][0]["rid"],
                "trace_id": requestz["requests"][0]["trace_id"],
                "phases": requestz["requests"][0]["phases"],
                "device_ms": requestz["requests"][0]["phases"].get(
                    "device_ms"),
                "events": [e["kind"]
                           for e in requestz["requests"][0]["events"]],
            } if requestz["requests"] else None),
            "poolz_blocks": poolz["pool"].get("blocks"),
            "poolz_ledger": poolz["scheduler"].get("ledger"),
            "poolz_scheduler": {
                "expected_new_ema": poolz["scheduler"]["expected_new_ema"],
                "queue_depth": poolz["scheduler"]["queue_depth"]},
            # Aggregation + introspection evidence: the merged status
            # block and the CR's latest reconcile outcome with its trace
            # id (joinable against /traces.json and JSON logs).
            "status_slice_workload": sample,
            "statusz_last_reconcile": reconcile_outcomes[-1]
                                      if reconcile_outcomes else None,
            "statusz_outcomes": len(outcomes),
            "statusz_trace_ids_present": all(
                o.get("trace_id") for o in reconcile_outcomes),
        }
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        fake.stop()
        worker_metrics.shutdown()

    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    return report


def main():
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--trace-out", metavar="PATH",
                        help="capture one webhook->controller->workload "
                             "lifecycle and write a merged Chrome trace to "
                             "PATH instead of running the full bench")
    parser.add_argument("--fleet", action="store_true",
                        help="with --trace-out: capture a two-replica serve "
                             "fleet instead — separate replica processes, "
                             "one shared trace id, Chrome timeline stitched "
                             "by the fleetz aggregator")
    parser.add_argument("--record-trace", metavar="PATH",
                        help="drive a short live burst through the paged "
                             "ingress and write its /requestz?format=jsonl "
                             "arrival capture to PATH (replayable via "
                             "python -m tools.sim --scenario replay "
                             "--replay-trace PATH) instead of running the "
                             "full bench")
    parser.add_argument("--slo-report", metavar="PATH",
                        help="drive a serve run + CR trajectory and write a "
                             "JSON SLO summary (time-to-Running p50/p99, "
                             "reconcile error rate, serve TTFT/tokens-per-"
                             "sec) to PATH instead of running the full bench")
    parser.add_argument("--check", nargs="?", const="__RUN__",
                        metavar="RESULTS_JSON",
                        help="regression gate: compare a bench results JSON "
                             "(default: run the workload bench now) against "
                             ".workload_last_good.json and exit nonzero when "
                             "a roofline-fraction / achieved-GB/s key "
                             "regressed >15%% the wrong way")
    args = parser.parse_args()

    if args.check:
        results = (None if args.check == "__RUN__"
                   else json.loads(Path(args.check).read_text()))
        sys.exit(check_results(results))

    if args.record_trace:
        # Pure-Python serve leg: no native daemons, no build needed.
        record_trace(args.record_trace)
        return
    if args.trace_out and args.fleet:
        # Pure-Python fleet: no native daemons involved, no build needed.
        fleet_trace_capture(args.trace_out)
        return
    nativelib.build_native()
    if args.trace_out:
        trace_capture(args.trace_out)
        return
    if args.slo_report:
        slo_report(args.slo_report)
        return

    # Workload first (VERDICT r1): the TPU half must not depend on anything
    # the control-plane bench does to the process.
    workload = workload_bench()

    parallel_rate, parallel_elapsed, parallel_p50, daemon_p50 = run_config(workers=8)
    serial_rate, serial_elapsed, serial_p50, _ = run_config(workers=1)
    # Same pair against a server with a 2ms/request RTT (kind/real API
    # server territory): architecture scaling shows once requests have
    # real latency to overlap.
    rtt_parallel_rate, _, rtt_parallel_p50, _ = run_config(workers=8, latency_ms=2)
    rtt_serial_rate, _, _, _ = run_config(workers=1, latency_ms=2)
    # Scale config: 2,000 CRs (10x the headline burst) — reconciles/s must
    # hold at one order of magnitude more objects (watch resume keeps
    # steady-state O(events), not O(CRs)).
    scale_rate, scale_elapsed, scale_p50, _ = run_config(
        workers=8, n_burst=2000, k_latency=10)

    result = {
        "metric": "reconciles_per_sec",
        "value": round(parallel_rate, 2),
        "unit": "reconciles/s",
        "vs_baseline": round(parallel_rate / serial_rate, 3),
        # The reference publishes no numbers and its Rust toolchain is
        # unavailable here, so "baseline" = this controller constrained to
        # the reference's serial one-reconcile-at-a-time architecture.
        "vs_baseline_definition": "8-worker vs same controller at 1 worker "
                                  "(reference architecture stand-in)",
        # Absolute rates are bound by the in-process Python API server.
        # fakeapi_version pins its cost profile: rates are comparable
        # across rounds ONLY at equal versions (v2 = real SSA with
        # managedFields/conflicts + 5 child-kind watch streams + Event
        # absorption; v1 was the thin pre-SSA fake, ~2x faster per CR).
        # The architecture ratios (vs_baseline, rtt2ms_vs_serial) are
        # version-independent signal.
        "server_bound_note": "rates bound by the in-process fake API "
                             "server (real SSA + child watches + events)",
        "fakeapi_version": FAKEAPI_VERSION,
        "bench_commit": _git_fingerprint(),
        "p50_apply_to_slice_ms": round(parallel_p50, 2),
        "daemon_reconcile_p50_ms": round(daemon_p50, 2),
        "burst_n": N_BURST,
        "burst_elapsed_s": round(parallel_elapsed, 3),
        "serial_baseline_reconciles_per_sec": round(serial_rate, 2),
        "serial_baseline_p50_ms": round(serial_p50, 2),
        "rtt2ms_reconciles_per_sec": round(rtt_parallel_rate, 2),
        "rtt2ms_vs_serial": round(rtt_parallel_rate / rtt_serial_rate, 3),
        "rtt2ms_p50_ms": round(rtt_parallel_p50, 2),
        "burst2000_reconciles_per_sec": round(scale_rate, 2),
        "burst2000_elapsed_s": round(scale_elapsed, 3),
        "burst2000_p50_ms": round(scale_p50, 2),
    }
    result.update(admission_bench())
    result.update(webhook_path_bench())
    result.update(workload)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
