"""CLI: ``python -m tools.mc`` explores, ``--replay <seed>`` re-runs one
schedule verbosely, ``--seed-bug leak`` demonstrates the seeded
refcount violation end to end (find it, print the seed, reproduce it
from that seed)."""

import argparse
import json
import sys
import time


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m tools.mc",
        description="Systematic-interleaving model checker for the "
                    "Scheduler/BlockAllocator serving core.")
    p.add_argument("--depth", type=int, default=9,
                   help="adversarial-action depth bound (default 9; the "
                        "quiescence tail past it is always run)")
    p.add_argument("--max", type=int, default=None, dest="max_n",
                   help="stop after this many complete interleavings")
    p.add_argument("--dedupe", action="store_true",
                   help="prune subtrees at revisited state fingerprints")
    p.add_argument("--keep-going", action="store_true",
                   help="collect every violation instead of stopping at "
                        "the first")
    p.add_argument("--seed-bug", choices=("leak",), default=None,
                   help="arm the seeded refcount bug (demo/CI fixture: "
                        "the run must FIND it and reproduce it)")
    p.add_argument("--replay", default=None, metavar="SCHEDULE",
                   help="re-run one comma-separated schedule seed "
                        "verbosely instead of exploring")
    p.add_argument("--violation-out", default=None, metavar="PATH",
                   help="write a violating schedule seed to PATH "
                        "(CI uploads it as an artifact)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable result on stdout")
    return p.parse_args(argv)


def _state_line(sys_, action):
    al = sys_.pool.allocator
    return (f"  {action:<8} queue={sys_.sched.queue_depth()} "
            f"resident={[s.rid for s in sys_.pool.slots if s is not None]} "
            f"parked={[r['request'].rid for r in sys_.pool.preempted]} "
            f"blocks(live={al.used()} cached={al.cached()}) "
            f"retired={sorted(sys_.retired)}")


def _replay(seed, spec, as_json):
    from tools.mc import run_schedule

    schedule = [a for a in seed.split(",") if a]
    print(f"tools.mc: replaying {len(schedule)}-action schedule")
    _sys, viol = run_schedule(
        schedule, spec,
        observer=None if as_json else
        (lambda s, a: print(_state_line(s, a))))
    if as_json:
        print(json.dumps({
            "schedule": schedule,
            "violation": (None if viol is None else
                          {"invariant": viol.invariant,
                           "detail": viol.detail})}))
    if viol is not None:
        print(f"tools.mc: VIOLATION [{viol.invariant}] {viol.detail}")
        print(f"tools.mc: at action {len(viol.schedule)} "
              f"({viol.schedule[-1]})")
        return 1
    print("tools.mc: schedule completed with every invariant intact")
    return 0


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    from tools.mc import ACTIONS, default_spec, explore, run_schedule

    spec = default_spec(bug=args.seed_bug)
    if args.replay is not None:
        return _replay(args.replay, spec, args.json)

    t0 = time.monotonic()
    res = explore(spec, depth=args.depth, max_interleavings=args.max_n,
                  dedupe=args.dedupe,
                  stop_at_first=not args.keep_going,
                  progress=None if args.json else (
                      lambda n: print(f"tools.mc: ... {n} interleavings",
                                      file=sys.stderr)))
    dt = time.monotonic() - t0
    if args.json:
        print(json.dumps({
            "interleavings": res.interleavings,
            "deduped": res.deduped,
            "actions_applied": res.actions_applied,
            "depth": res.depth,
            "seconds": round(dt, 3),
            "violations": [{"invariant": v.invariant, "detail": v.detail,
                            "seed": v.seed()} for v in res.violations]}))
    else:
        extra = (f" ({res.deduped} subtrees deduped)" if args.dedupe
                 else "")
        print(f"tools.mc: explored {res.interleavings} interleavings of "
              f"{{{','.join(ACTIONS)}}} to depth {res.depth} in {dt:.1f}s"
              f"{extra} — {len(res.violations)} violation(s)")
    if not res.violations:
        return 0
    v = res.violations[0]
    seed = v.seed()
    print(f"tools.mc: VIOLATION [{v.invariant}] {v.detail}")
    print(f"tools.mc: replay with: python -m tools.mc"
          + (" --seed-bug " + args.seed_bug if args.seed_bug else "")
          + f" --replay '{seed}'")
    if args.violation_out:
        with open(args.violation_out, "w", encoding="utf-8") as f:
            f.write(json.dumps({"invariant": v.invariant,
                                "detail": v.detail, "seed": seed,
                                "seed_bug": args.seed_bug}, indent=2)
                    + "\n")
        print(f"tools.mc: schedule written to {args.violation_out}")
    # The seeded-bug demo must close the loop: the printed seed alone
    # reproduces the violation from scratch.
    if args.seed_bug:
        _sys2, viol2 = run_schedule(v.schedule, default_spec(
            bug=args.seed_bug))
        ok = viol2 is not None and viol2.invariant == v.invariant
        print("tools.mc: seed replay "
              + ("REPRODUCED the violation" if ok
                 else "FAILED to reproduce (nondeterminism bug!)"))
    return 1


if __name__ == "__main__":
    sys.exit(main())
