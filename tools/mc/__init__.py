"""tools.mc -- systematic-interleaving model checker for the serving core.

Runs the REAL ``serving.Scheduler`` + ``serving.BlockAllocator`` (plus
the real PagedPool host-side admission / preemption / prefix-cache /
quarantine policy -- see ``harness.MCPool``) through every bounded-depth
interleaving of the six-action alphabet {submit, step, preempt, crash,
drain, snap}, asserting the test-pinned invariants (refcount
conservation, block-partition soundness, busy+idle==wall ledger
conservation, snapshot coherence, scheduling-independent token streams,
progress) after every action of every interleaving.

``python -m tools.mc`` explores; ``python -m tools.mc --replay <seed>``
re-runs one schedule verbosely.  A violating schedule IS its replay
seed: the checker prints it and exits nonzero.
"""

from .harness import (  # noqa: F401
    ACTIONS,
    InvariantViolation,
    MCPool,
    MCSystem,
    Violation,
    default_spec,
    explore,
    expected_stream,
    run_schedule,
)
