"""The systematic-interleaving harness: real Scheduler + real allocator.

The model under check is NOT a mock.  ``MCPool`` subclasses the real
``PagedPool`` and inherits its entire host-side policy surface verbatim
— admission capacity math (``admits``/``_reserve_blocks``), prefix-cache
planning and COW pinning (``_prefix_plan``/``admit``), refcounted block
registration (``_register_full``), the preemption victim policy
(``preempt_one``/``_preempt``), lazy overcommit growth
(``_capacity_fold``), crash quarantine (``quarantine`` →
``BlockAllocator.quarantine_to_cache``) and retirement
(``_on_retire``) — all running against a real ``BlockAllocator``.  Only
the device dispatch is replaced: ``step_round`` advances slots with a
deterministic token oracle (a pure function of ``(rid, position)``,
exactly the independence contract the real greedy engine pins), so one
scheduling round costs microseconds instead of a jit dispatch and the
explorer can afford tens of thousands of interleavings.

``MCSystem`` wraps one ``Scheduler(MCPool)`` pair and exposes the
seven-action alphabet as atomic transitions at the code's real round
boundaries:

- ``submit``  — ``Scheduler.submit`` of the next workload request
- ``step``    — one full ``Scheduler.step`` (shed → admit → round →
  preempt-drain → ledger fold)
- ``preempt`` — an external ``pool.preempt_one()`` between rounds (the
  capacity/priority eviction seam, fired at an adversarial point)
- ``crash``   — arm ``MCPool`` to raise inside the next round, then
  step: the failure flows through ``Scheduler.step``'s REAL recovery
  boundary (``_recover`` → ``quarantine`` → requeue)
- ``swap``    — force one HBM-cached block through the demotion seam
  (``pool.demote_lru(1)``): the host-tier eviction path fired at an
  adversarial point, so promotion/demotion races with admission,
  preemption, and crash recovery are all explored
- ``drain``   — graceful drain: quarantine residents, requeue, then
  ``Scheduler.reset("drain")``
- ``snap``    — a handler-thread observation: ``Scheduler.snapshot()``
  + ``pool.snapshot()`` coherence checks

After EVERY action ``check_invariants`` asserts the pinned global
invariants; a failed one raises ``InvariantViolation`` and the action
trace so far IS the replay seed (``run_schedule`` re-executes it).

States are rebuilt by replay rather than copied: ``Scheduler`` owns a
``threading.Lock`` (not deep-copyable), and replay-from-scratch keeps
the checked object the production class, not a fork of it.
"""

from __future__ import annotations

import dataclasses
import math

from tpu_bootstrap.workload.model import ModelConfig
from tpu_bootstrap.workload.serving import (
    BlockAllocator,
    PagedPool,
    Request,
    Scheduler,
    _majority_chunk,
    _bucket_down,
    key_fingerprint,
)

ACTIONS = ("submit", "step", "preempt", "swap", "crash", "drain", "snap")

# Params-free config: the oracle never runs the model, but the real
# Scheduler prices ledger tokens through flops_model(cfg) and the real
# validate() gates against max_seq_len, so a real config is required.
_MC_CFG = ModelConfig(vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
                      embed_dim=16, mlp_dim=32, max_seq_len=64)


def _oracle(rid: int, position: int, vocab: int) -> int:
    """Deterministic next token for ``rid`` at stream ``position`` —
    the model stand-in. Pure in (rid, position): a preempted row's
    resume MUST reproduce the same continuation, which is exactly the
    byte-identical-streams invariant the checker asserts."""
    return (rid * 1000003 + position * 7919) % vocab


class InvariantViolation(AssertionError):
    def __init__(self, invariant: str, detail: str):
        super().__init__(f"{invariant}: {detail}")
        self.invariant = invariant
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class WorkloadItem:
    rid: int
    tokens: tuple
    max_new: int
    priority: int = 0


@dataclasses.dataclass(frozen=True)
class MCSpec:
    """One model-checking configuration: the workload plus the pool
    shape. Small on purpose — state-space size is exponential in all of
    it."""
    workload: tuple
    batch_size: int = 2
    kv_blocks: int = 5
    block_size: int = 4
    prefill_budget: int = 4
    expected_new: int = 2
    overcommit: bool = True
    max_crashes: int = 1
    host_blocks: int = 2
    bug: str | None = None


def default_spec(bug: str | None = None) -> MCSpec:
    """The checked-in workload: three requests over two slots and five
    blocks — a shared first block (prefix-cache refcount sharing), a
    higher-priority late arrival (priority-admission preemption), and a
    prompt whose plan COWs the shared block (the pin/unpin seam)."""
    return MCSpec(
        workload=(
            WorkloadItem(rid=1, tokens=(1, 2, 3, 4, 5, 6), max_new=3),
            WorkloadItem(rid=2, tokens=(1, 2, 3, 4, 9, 10), max_new=2,
                         priority=1),
            WorkloadItem(rid=3, tokens=(1, 2, 3, 4), max_new=2),
        ),
        bug=bug,
    )


def expected_stream(spec: MCSpec, rid: int) -> list:
    """The one continuation ``rid`` may ever produce, independent of
    scheduling (admission order, chunking, preemption, crash-resume)."""
    w = next(w for w in spec.workload if w.rid == rid)
    return [_oracle(rid, len(w.tokens) + k, _MC_CFG.vocab_size)
            for k in range(w.max_new)]


class MCPool(PagedPool):
    """PagedPool with the device replaced by the token oracle. Every
    allocator/cache/preemption/quarantine code path is the inherited
    real one; only ``__init__`` (no params/arrays), ``step_round`` (no
    jit dispatch) and ``_record_block_gauges`` (no registry churn per
    explored state) are overridden.

    ``bug="leak"`` arms the seeded invariant violation the tests and
    ``--seed-bug`` reproduce: the first retirement drops one table
    reference before freeing, leaking a live block (refcount 1, no
    owner) — the refcount-conservation invariant must catch it."""

    def __init__(self, cfg: ModelConfig, batch_size: int, kv_blocks: int,
                 block_size: int, *, prefill_budget: int = 4,
                 host_blocks: int = 0, bug: str | None = None):
        self.cfg = cfg
        self.batch_size = batch_size
        self.block_size = block_size
        self.kv_quant = False
        self.eos_id = None
        self.temperature, self.top_k, self.top_p = 0.0, 0, 1.0
        self.key = None
        self.params = None
        self.draft_params = None
        self.draft_cfg = None
        self.gamma = 0
        self._spec = False
        self.paged_kernel = False
        self.prefix_cache = True
        self.prefill_budget = prefill_budget
        self.chunk_hint = None
        self.pools = ()       # no device arrays: quarantine sees "alive"
        self.dpools = None
        self.allocator = BlockAllocator(kv_blocks, block_size)
        self.slots = [None] * batch_size
        self.preempted = []
        self.request_cached_tokens = {}
        self._pre_rr = 0
        self._kv_bytes_per_tok = 1.0
        self._prefill_ms_per_tok = None
        self.stats = {"rounds": 0, "slot_steps": 0, "active_slot_steps": 0,
                      "preemptions": 0, "grown_blocks": 0, "cow_copies": 0,
                      "prompt_tokens": 0, "prefix_hit_tokens": 0,
                      "prefix_hit_requests": 0, "blocks_peak": 0,
                      "defrags": 0}
        self._host_init(host_blocks)
        self.crash_next_round = False
        self._bug = bug
        self._bug_armed = bug is not None

    # -- the only mocked seam: token generation -----------------------------

    def step_round(self) -> dict:
        active = [s for s in self.slots if s is not None]
        if not active:
            return {}
        if self.crash_next_round:
            # The injected engine failure: raised where the real
            # pool.device fault site sits (before dispatch, arrays
            # survive), unwinding into Scheduler.step's recovery path.
            self.crash_next_round = False
            raise RuntimeError("mc: injected device failure")
        self.stats["rounds"] += 1
        self._mc_prefill_phase()
        dec = [s for s in self.slots
               if s is not None and not self._prefilling(s)
               and s.remaining > 0]
        chunk = 0
        if dec:
            chunk = _majority_chunk(dec, self.cfg.max_seq_len)
            if any(self._prefilling(s)
                   for s in self.slots if s is not None):
                chunk = min(chunk, _bucket_down(self.prefill_budget))
            if self.chunk_hint is not None:
                chunk = min(chunk, _bucket_down(max(1, self.chunk_hint)))
            dec = self._capacity_fold(
                dec, lambda s: len(s.history) + min(chunk, s.remaining) - 1)
        if not dec:
            self._register_phase()
            self._record_block_gauges()
            return {}
        decoding = {id(s) for s in dec}
        out = _OracleOut(self.slots, decoding, chunk,
                         self.cfg.vocab_size)
        self.stats["slot_steps"] += self.batch_size * chunk
        self.stats["active_slot_steps"] += sum(
            min(chunk, s.remaining) for s in dec)
        counts = [chunk if (s is not None and id(s) in decoding) else 0
                  for s in self.slots]
        events = self._emit_events(out, 0, counts=counts)
        self._register_phase()
        self._record_block_gauges()
        return events

    def _mc_prefill_phase(self) -> None:
        # PagedPool._prefill_phase minus the device: same budget, same
        # round-robin fairness cursor, same ledger attribution.
        budget = self.prefill_budget
        pre = [(i, s) for i, s in enumerate(self.slots)
               if s is not None and self._prefilling(s)]
        if not pre:
            return
        start = self._pre_rr % len(pre)
        self._pre_rr += 1
        for _i, s in pre[start:] + pre[:start]:
            while budget > 0 and self._prefilling(s):
                w = _bucket_down(
                    min(s.prompt_len - 1 - s.prefilled, budget))
                s.prefilled += w
                s.prefill_chunks += 1
                budget -= w
                self._ledger_add(s.rid, "prefill", w)

    def _on_retire(self, i: int, s) -> None:
        if self._bug_armed and s.blocks:
            # Seeded violation: one table reference vanishes before the
            # free — the block stays live in the allocator with nobody
            # owning it (the classic leaked-decref bug).
            self._bug_armed = False
            s.blocks = s.blocks[:-1]
        super()._on_retire(i, s)

    def _record_block_gauges(self) -> None:
        # Exploration runs thousands of states: skip the global metric
        # registry churn, keep the stat the invariants read.
        self.stats["blocks_peak"] = self.allocator.stats["peak_used"]

    # -- host-tier seams: no device arrays, so transfers are stubs ----------

    def _host_fetch(self, bid: int) -> dict:
        # Block CONTENT is the oracle's business; only the accounting
        # shape (one entry, its byte ledger) matters to the invariants.
        return {"t": None, "d": None,
                "bytes": self.block_size * self._kv_bytes_per_tok}

    def _host_restore(self, ids: list, entries: list) -> int:
        return 0

    def _note_bw(self, nbytes: float, secs: float) -> None:
        # Wall-clock bandwidth would make the swap-vs-recompute arm —
        # and therefore explored state — nondeterministic across runs;
        # the env-seeded constant keeps every interleaving's future a
        # pure function of its fingerprint.
        return


class _OracleOut:
    """Duck-typed (B, chunk) round output: out[i, :keep].tolist() is
    what ``_emit_events`` reads — served lazily from the oracle."""

    def __init__(self, slots, decoding, chunk, vocab):
        self._rows = {}
        for i, s in enumerate(slots):
            if s is not None and id(s) in decoding:
                self._rows[i] = (s.rid, len(s.history))
        self._chunk = chunk
        self._vocab = vocab

    def __getitem__(self, key):
        i, sl = key
        rid, base = self._rows[i]
        toks = [_oracle(rid, base + j, self._vocab)
                for j in range(self._chunk)][sl]
        return _TokList(toks)


class _TokList(list):
    def tolist(self):
        return list(self)


class MCSystem:
    """One explorable execution: real Scheduler over an MCPool, the
    action alphabet, and the per-action invariant checks."""

    def __init__(self, spec: MCSpec):
        self.spec = spec
        self.pool = MCPool(_MC_CFG, spec.batch_size, spec.kv_blocks,
                           spec.block_size,
                           prefill_budget=spec.prefill_budget,
                           host_blocks=spec.host_blocks,
                           bug=spec.bug)
        self.sched = Scheduler(self.pool, overcommit=spec.overcommit,
                               expected_new=spec.expected_new,
                               ema_alpha=0.5)
        self.requests = [Request(rid=w.rid, tokens=list(w.tokens),
                                 max_new=w.max_new, priority=w.priority)
                         for w in spec.workload]
        self.next_submit = 0
        self.streams: dict = {}      # rid -> generated tokens at retire
        self.retired: set = set()
        self.crashes = 0
        self.drained = False
        self.last_action: str | None = None
        self.trace: list = []

    # -- transitions --------------------------------------------------------

    def enabled(self) -> list:
        acts = []
        if self.drained:
            return ["snap"] if self.last_action != "snap" else []
        if self.next_submit < len(self.requests):
            acts.append("submit")
        busy = (self.sched.pending() or self.pool.has_active()
                or bool(self.pool.preempted))
        if busy:
            acts.append("step")
        if self.pool.has_active():
            acts.append("preempt")
            if self.crashes < self.spec.max_crashes:
                acts.append("crash")
        if (self.pool.host is not None
                and self.pool.allocator.cached() > 0):
            # Adversarial demotion: evict an HBM-cached block through
            # the host-tier seam between any two other actions.
            acts.append("swap")
        if busy:
            acts.append("drain")
        if self.last_action != "snap":
            # Two consecutive snapshots observe the identical state —
            # a sound reduction for a read-only action.
            acts.append("snap")
        return acts

    def apply(self, name: str) -> None:
        self.trace.append(name)
        if name == "submit":
            self.sched.submit(self.requests[self.next_submit])
            self.next_submit += 1
        elif name == "step":
            self._fold_events(self.sched.step())
        elif name == "preempt":
            self.pool.preempt_one()
        elif name == "swap":
            self.pool.demote_lru(1)
        elif name == "crash":
            self.crashes += 1
            self.pool.crash_next_round = True
            self._fold_events(self.sched.step())
        elif name == "drain":
            self.drained = True
            self.sched.requeue(self.pool.quarantine(reason="drain"))
            self.sched.reset(reason="drain")
            if self.pool.allocator.used() != 0:
                raise InvariantViolation(
                    "drain-leak",
                    f"{self.pool.allocator.used()} live blocks survived "
                    "quarantine_to_cache")
        elif name == "snap":
            self._check_snapshots()
        else:
            raise ValueError(f"unknown action {name!r} "
                             f"(alphabet: {', '.join(ACTIONS)})")
        self.last_action = name
        check_invariants(self)

    def _fold_events(self, events: dict) -> None:
        for rid, ev in events.items():
            gen = list(ev["generated"])
            exp = expected_stream(self.spec, rid)
            if gen != exp[:len(gen)]:
                raise InvariantViolation(
                    "stream-determinism",
                    f"rid {rid} diverged: got {gen}, expected prefix "
                    f"of {exp} — a resume replayed different tokens")
            if ev.get("done"):
                if rid in self.retired:
                    raise InvariantViolation(
                        "stream-once",
                        f"rid {rid} retired twice — a crash or preempt "
                        "resurrected a finished stream")
                self.retired.add(rid)
                self.streams[rid] = gen

    # -- observations -------------------------------------------------------

    def _check_snapshots(self) -> None:
        snap = self.sched.snapshot()
        if snap["queue_depth"] != len(snap["waiting"]):
            raise InvariantViolation(
                "snapshot-coherence",
                f"queue_depth {snap['queue_depth']} != "
                f"len(waiting) {len(snap['waiting'])}")
        prios = [w["priority"] for w in snap["waiting"]]
        if prios != sorted(prios, reverse=True):
            raise InvariantViolation(
                "snapshot-coherence",
                f"waiting not in admission order: priorities {prios}")
        led = snap["ledger"]
        if abs(led["busy_ms"] + led["idle_ms"] - led["wall_ms"]) > 5e-3:
            raise InvariantViolation(
                "ledger-conservation",
                f"snapshot ledger: busy {led['busy_ms']} + idle "
                f"{led['idle_ms']} != wall {led['wall_ms']}")
        ps = self.pool.snapshot()
        b = ps["blocks"]
        if b["live"] + b["cached"] + b["free"] != b["total"]:
            raise InvariantViolation(
                "snapshot-coherence",
                f"blocks live {b['live']} + cached {b['cached']} + free "
                f"{b['free']} != total {b['total']}")
        if b["available"] != b["free"] + b["cached"]:
            raise InvariantViolation(
                "snapshot-coherence",
                f"blocks available {b['available']} != free + cached")
        if ps["active"] != len(ps["slots"]) or (
                ps["free_slots"] != ps["batch_size"] - ps["active"]):
            raise InvariantViolation(
                "snapshot-coherence",
                f"active {ps['active']} / free_slots {ps['free_slots']} "
                f"inconsistent with {len(ps['slots'])} slot rows")
        d = ps["cache_digest"]
        if d["blocks"] != len(d["fps"]):
            raise InvariantViolation(
                "snapshot-coherence",
                f"cache digest blocks {d['blocks']} != {len(d['fps'])} "
                "fingerprints")
        hp = self.pool.host
        h = ps["host"]
        if hp is not None:
            if h["blocks"] != len(hp) or h["bytes"] != hp.bytes:
                raise InvariantViolation(
                    "snapshot-coherence",
                    f"host snapshot blocks/bytes {h['blocks']}/"
                    f"{h['bytes']} != live tier {len(hp)}/{hp.bytes}")
            hd = d.get("host")
            if hd is None or hd["blocks"] != len(hd["fps"]):
                raise InvariantViolation(
                    "snapshot-coherence",
                    f"host digest incoherent: {hd}")
        elif h["blocks"] or h["capacity"]:
            raise InvariantViolation(
                "snapshot-coherence",
                f"tier-off snapshot advertises host blocks: {h}")

    def fingerprint(self) -> tuple:
        """Scheduling-relevant state only (no wall-clock values): equal
        fingerprints make equal futures, so the explorer may prune."""
        al = self.pool.allocator
        with self.sched._lock:
            waiting = tuple(sorted(
                (e[2], e[3].rid, e[0], len(e[4] or ()))
                for e in self.sched._waiting))
            ema = round(self.sched._ema, 6)
        return (
            self.next_submit, self.crashes, self.drained,
            self.sched._fail_streak, waiting, ema,
            tuple((s.rid, s.prefilled, len(s.history), s.remaining,
                   tuple(s.blocks), s.registered, s.n_shared)
                  if s is not None else None for s in self.pool.slots),
            tuple(sorted(al._free)),
            tuple(sorted(al._ref.items())),
            tuple(al._cached),
            tuple(sorted(al._index)),
            tuple((r["request"].rid, len(r["preload"]))
                  for r in self.pool.preempted),
            tuple(sorted(self.retired)),
            # Host tier in LRU ORDER: which keys are parked AND their
            # eviction order both shape future promotions and drops.
            tuple(key_fingerprint(k)
                  for k in (self.pool.host.keys()
                            if self.pool.host is not None else ())),
        )


# -- invariants --------------------------------------------------------------


def check_invariants(sys_: MCSystem) -> None:
    al = sys_.pool.allocator
    free = list(al._free)
    live = dict(al._ref)
    cached = list(al._cached)
    ids = free + list(live) + cached
    if len(set(ids)) != len(ids):
        raise InvariantViolation(
            "block-partition",
            f"a block sits in two allocator sets: free={sorted(free)} "
            f"live={sorted(live)} cached={sorted(cached)}")
    if set(ids) != set(range(1, al.num_blocks + 1)):
        raise InvariantViolation(
            "block-partition",
            f"free+live+cached is not the id space 1..{al.num_blocks}: "
            f"{sorted(ids)}")
    # Refcount conservation: block-table references are the ONLY
    # legitimate owners between actions.
    refs: dict = {}
    for s in sys_.pool.slots:
        if s is None:
            continue
        own = list(s.blocks)
        if len(set(own)) != len(own):
            raise InvariantViolation(
                "block-uniqueness",
                f"rid {s.rid} table holds a duplicate block: {own}")
        for b in own:
            refs[b] = refs.get(b, 0) + 1
    if refs != live:
        raise InvariantViolation(
            "refcount-conservation",
            f"table references {refs} != allocator refcounts {live}")
    # Index maps stay inverse bijections; cached blocks are exactly the
    # registered-but-unowned ones.
    if {al._index[k]: k for k in al._index} != dict(al._key_of.items()):
        raise InvariantViolation(
            "cache-index", "_index and _key_of are not inverse maps")
    for bid in cached:
        if bid not in al._key_of:
            raise InvariantViolation(
                "cache-index", f"cached block {bid} has no content key")
    # Slot sanity: coverage + monotone budgets.
    bs = sys_.pool.block_size
    for s in sys_.pool.slots:
        if s is None:
            continue
        written = (s.prefilled if sys_.pool._prefilling(s)
                   else len(s.history) - 1)
        if len(s.blocks) * bs < written:
            raise InvariantViolation(
                "block-coverage",
                f"rid {s.rid}: {len(s.blocks)} blocks cover "
                f"{len(s.blocks) * bs} positions < {written} written")
        if s.remaining < 0 or s.registered > len(s.blocks):
            raise InvariantViolation(
                "slot-sanity",
                f"rid {s.rid}: remaining={s.remaining} "
                f"registered={s.registered} blocks={len(s.blocks)}")
    # Host-tier soundness: the tier is bounded, its byte ledger matches
    # its entries, and every entry is a well-formed serialized block
    # under a full-strength chain key. The HBM partition check above is
    # unaffected by the tier — host entries are content COPIES keyed by
    # chain key, never aliases of an allocator block id, so dual
    # residency (same key cached on HBM and parked on host) is legal
    # and the tiers can never disagree about ownership.
    host = sys_.pool.host
    if host is not None:
        if len(host) > host.capacity:
            raise InvariantViolation(
                "host-capacity",
                f"host tier holds {len(host)} blocks > capacity "
                f"{host.capacity}")
        total = sum(e["bytes"] for e in host._entries.values())
        if total != host.bytes:
            raise InvariantViolation(
                "host-accounting",
                f"host byte ledger {host.bytes} != entry sum {total}")
        for k, e in host._entries.items():
            if len(k) != 32 or "bytes" not in e:
                raise InvariantViolation(
                    "host-entry",
                    f"malformed host entry under key {k!r}: {e}")
    # Ledger conservation on the raw (unrounded) ledger.
    led = sys_.sched.ledger
    if not math.isclose(led["busy_ms"] + led["idle_ms"], led["wall_ms"],
                        rel_tol=1e-9, abs_tol=1e-6):
        raise InvariantViolation(
            "ledger-conservation",
            f"busy {led['busy_ms']} + idle {led['idle_ms']} != wall "
            f"{led['wall_ms']}")
    attributed = (sum(sys_.sched.device_ms_by_rid.values())
                  + led["retired_device_ms"])
    if not math.isclose(attributed, led["attributed_ms"],
                        rel_tol=1e-9, abs_tol=1e-6):
        raise InvariantViolation(
            "ledger-conservation",
            f"per-rid device ms {attributed} != attributed "
            f"{led['attributed_ms']}")
    # Request conservation: one home per rid, and retirement is final.
    with sys_.sched._lock:
        queued = [e[3].rid for e in sys_.sched._waiting]
    resident = [s.rid for s in sys_.pool.slots if s is not None]
    parked = [r["request"].rid for r in sys_.pool.preempted]
    homes = queued + resident + parked
    if len(set(homes)) != len(homes):
        raise InvariantViolation(
            "request-conservation",
            f"a request lives in two places: queued={queued} "
            f"resident={resident} preempted={parked}")
    twice = sys_.retired.intersection(homes)
    if twice:
        raise InvariantViolation(
            "request-conservation",
            f"retired requests re-entered the system: {sorted(twice)}")


# -- exploration -------------------------------------------------------------


@dataclasses.dataclass
class Violation:
    schedule: tuple
    invariant: str
    detail: str

    def seed(self) -> str:
        return ",".join(self.schedule)


@dataclasses.dataclass
class ExploreResult:
    interleavings: int      # complete interleavings fully executed
    violations: list
    deduped: int            # subtrees pruned at revisited states
    actions_applied: int
    depth: int


def _progress_bound(spec: MCSpec) -> int:
    return 32 + 8 * len(spec.workload) + 2 * sum(
        w.max_new + len(w.tokens) for w in spec.workload)


def _finish(sys_: MCSystem) -> Violation | None:
    """Close out one complete interleaving: drive the system to
    quiescence with plain submits/steps (no more adversarial actions)
    and require every request to retire with its oracle stream — the
    no-deadlock/no-livelock and scheduling-independence checks. The
    tail actions append to the trace, so a violation found here still
    replays from its printed seed."""
    if sys_.drained:
        return None  # drained executions legitimately abort streams
    bound = _progress_bound(sys_.spec)
    steps = 0
    while (sys_.next_submit < len(sys_.requests) or sys_.sched.pending()
           or sys_.pool.has_active() or sys_.pool.preempted):
        if steps > bound:
            return Violation(
                tuple(sys_.trace), "progress",
                f"not quiescent after {bound} extra steps: "
                f"queue={sys_.sched.queue_depth()} "
                f"active={sys_.pool.has_active()}")
        try:
            sys_.apply("submit" if sys_.next_submit < len(sys_.requests)
                       else "step")
        except InvariantViolation as e:
            return Violation(tuple(sys_.trace), e.invariant, e.detail)
        steps += 1
    for w in sys_.spec.workload:
        if w.rid not in sys_.retired:
            return Violation(
                tuple(sys_.trace), "progress",
                f"rid {w.rid} never retired (lost request)")
        if sys_.streams[w.rid] != expected_stream(sys_.spec, w.rid):
            return Violation(
                tuple(sys_.trace), "stream-determinism",
                f"rid {w.rid} final stream {sys_.streams[w.rid]} != "
                f"{expected_stream(sys_.spec, w.rid)}")
    return None


def run_schedule(schedule, spec: MCSpec,
                 observer=None) -> tuple:
    """Execute one action sequence from scratch. Returns
    (system, Violation | None).  ``observer(sys_, action)`` runs after
    every successful action (the verbose replay hook)."""
    sys_ = MCSystem(spec)
    for a in schedule:
        try:
            sys_.apply(a)
        except InvariantViolation as e:
            return sys_, Violation(tuple(sys_.trace), e.invariant,
                                   e.detail)
        if observer is not None:
            observer(sys_, a)
    return sys_, None


def explore(spec: MCSpec, *, depth: int = 8,
            max_interleavings: int | None = None, dedupe: bool = False,
            stop_at_first: bool = True,
            progress=None) -> ExploreResult:
    """Bounded-depth DFS over the enabled-action tree. Each node is
    rebuilt by replaying its prefix (states are not copyable — see the
    module docstring); every complete interleaving additionally runs
    the ``_finish`` progress/determinism checks. With ``dedupe``,
    subtrees rooted at an already-visited state fingerprint are pruned
    (counted, not explored)."""
    stack: list = [()]
    seen: set = set()
    count = deduped = applied = 0
    violations: list = []
    while stack:
        prefix = stack.pop()
        sys_ = MCSystem(spec)
        bad = None
        try:
            for a in prefix:
                sys_.apply(a)
                applied += 1
        except InvariantViolation as e:
            bad = Violation(tuple(sys_.trace), e.invariant, e.detail)
        if bad is not None:
            count += 1
            violations.append(bad)
            if stop_at_first:
                break
            continue
        acts = sys_.enabled()
        if len(prefix) >= depth or not acts:
            count += 1
            if progress is not None and count % 1000 == 0:
                progress(count)
            v = _finish(sys_)
            if v is not None:
                violations.append(v)
                if stop_at_first:
                    break
            if max_interleavings and count >= max_interleavings:
                break
            continue
        if dedupe:
            fp = (sys_.fingerprint(), depth - len(prefix))
            if fp in seen:
                deduped += 1
                continue
            seen.add(fp)
        for a in reversed(acts):
            stack.append(prefix + (a,))
    return ExploreResult(interleavings=count, violations=violations,
                         deduped=deduped, actions_applied=applied,
                         depth=depth)
