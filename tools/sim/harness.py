"""The fleet digital twin: a discrete-event simulator that drives the
REAL control-plane policy objects at scales no CI fleet can host.

What is real and what is synthetic
----------------------------------

Real (imported, not reimplemented — the point of the exercise):

* ``FleetRouter`` placement (`_place`: longest fresh digest match via
  the real ``digest_match_len`` chain walk, least load on ties), its
  ``CircuitBreaker`` state machines, the scrape/fold plane
  (``scrape_once(now=)`` with only the HTTP transport stubbed), the
  hedge gate (``_beat_stalled``) and the ``AutoscaleController``
  hysteresis driven through the real ``autoscale_once(burn=, now=)``.
* ``SloEngine`` multi-window burn rates, firing/resolved transitions.
* ``faults`` injection: the stubbed scrape leg still fires the
  ``router.scrape`` site and synthetic dispatch fires ``sim.dispatch``,
  so ``TPUBC_FAULT`` schedules compose with scenarios.

Synthetic: the replicas. Each is a deterministic c-slot server whose
service times come from the repo's MEASURED cost models — one token's
prefill/decode priced by ``flops_model`` over ``telemetry.peak_tflops``
at observed MFUs (``TPUBC_SIM_MFU_PREFILL`` / ``_DECODE``), the
host-tier swap arm priced at ``telemetry.host_xfer_gbps`` against the
config's KV bytes/token (the cheaper of swap vs recompute wins, the
``serve_preempt_cost`` arms) — and whose prefix cache is a real
radix-chained fingerprint LRU (``block_hash``/``key_fingerprint``), so
the digests the router scrapes and scores are honest content digests.

Everything runs on ONE virtual monotonic clock injected through
``telemetry.set_clock`` — zero wall sleeps, and every ``now_us()``
stamp inside snapshots and alert transitions is virtual time, which is
what makes a scenario report byte-identical run to run.

The tools.mc contract carries over: a violated invariant prints a
STANDALONE replay seed (``scenario:rN:sN[:bug=...]``) that reproduces
the run from scratch, and ``--seed-bug limit-cycle`` plants a
pathological autoscaler (no cooldown, 1-tick streaks) the run must
find and then reproduce from its own printed seed.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import math
import os
import random
from collections import OrderedDict, deque

from tpu_bootstrap import telemetry
from tpu_bootstrap.workload import faults
from tpu_bootstrap.workload.fleetz import SloEngine
from tpu_bootstrap.workload.model import ModelConfig, flops_model
from tpu_bootstrap.workload.router import AutoscaleController, FleetRouter
from tpu_bootstrap.workload.serving import (block_hash, digest_match_len,
                                            key_fingerprint)

SCENARIOS = ("diurnal", "hot-prefix", "crash-cascade", "slow-drip",
             "limit-cycle", "replay")
BUGS = ("limit-cycle",)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


# ---- spec + seed grammar ------------------------------------------------


@dataclasses.dataclass
class SimSpec:
    """One fully-determined run. ``seed_str()`` is the replay seed: the
    whole simulation is a pure function of this string."""

    scenario: str = "diurnal"
    replicas: int = 100
    seed: int = 0
    bug: str | None = None
    duration_s: float | None = None  # None = the scenario's default
    trace: str | None = None         # --replay-trace arrivals file

    def seed_str(self) -> str:
        s = f"{self.scenario}:r{self.replicas}:s{self.seed}"
        if self.duration_s is not None:
            s += f":d{self.duration_s:g}"
        if self.bug:
            s += f":bug={self.bug}"
        return s


def parse_seed(seed: str) -> SimSpec:
    """``scenario:rN:sN[:dSECS][:bug=NAME]`` -> SimSpec (the printed
    violation seed's grammar; inverse of ``SimSpec.seed_str``)."""
    parts = seed.split(":")
    if not parts or parts[0] not in SCENARIOS:
        raise ValueError(f"bad seed {seed!r}: unknown scenario")
    spec = SimSpec(scenario=parts[0])
    for p in parts[1:]:
        if p.startswith("r"):
            spec.replicas = int(p[1:])
        elif p.startswith("s"):
            spec.seed = int(p[1:])
        elif p.startswith("d"):
            spec.duration_s = float(p[1:])
        elif p.startswith("bug="):
            if p[4:] not in BUGS:
                raise ValueError(f"bad seed {seed!r}: unknown bug")
            spec.bug = p[4:]
        else:
            raise ValueError(f"bad seed {seed!r}: unknown part {p!r}")
    return spec


@dataclasses.dataclass
class Violation:
    invariant: str
    detail: str
    spec: SimSpec

    def seed(self) -> str:
        return self.spec.seed_str()


# ---- virtual clock ------------------------------------------------------


class VirtualClock:
    """The injectable monotonic clock (telemetry.set_clock hook). The
    event loop owns it; nothing else may move it."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance_to(self, t: float) -> None:
        if t < self.t - 1e-9:
            raise RuntimeError(f"virtual clock moved backwards: "
                               f"{self.t} -> {t}")
        self.t = max(self.t, t)


# ---- cost model ---------------------------------------------------------

# The shape the service times are priced for: a 7B-class decoder
# (32 x 4096, GQA 8) — big enough that prefill/decode/swap land in the
# regimes the real engine measures, priced by the SAME flops_model /
# peak_tflops pair every MFU number in the repo reads.
_COST_CFG = dict(vocab_size=32000, num_layers=32, num_heads=32,
                 head_dim=128, embed_dim=4096, mlp_dim=11008,
                 max_seq_len=4096, num_kv_heads=8)


class CostModel:
    """Per-token service-time price list with provenance. MFUs default
    to the serving engine's observed operating points (prefill compute
    bound, decode memory bound) and are operator-overridable the same
    way the roofline denominators are."""

    def __init__(self):
        cfg = ModelConfig(**_COST_CFG)
        fl = flops_model(cfg)
        peak = telemetry.peak_tflops() * 1e12
        self.mfu_prefill = _env_float("TPUBC_SIM_MFU_PREFILL", 0.55)
        self.mfu_decode = _env_float("TPUBC_SIM_MFU_DECODE", 0.08)
        self.prefill_s_per_tok = fl["prefill"] / (peak * self.mfu_prefill)
        self.decode_s_per_tok = fl["decode"] / (peak * self.mfu_decode)
        # KV bytes/token (bf16 k+v over all layers at the GQA width):
        # the swap arm's numerator, moved at host_xfer_gbps.
        kv_heads = cfg.num_kv_heads or cfg.num_heads
        self.kv_bytes_per_tok = 2 * cfg.num_layers * kv_heads \
            * cfg.head_dim * 2
        self.swap_s_per_tok = self.kv_bytes_per_tok / (
            telemetry.host_xfer_gbps() * 1e9)
        self.params = fl["params"]

    def provenance(self) -> dict:
        return {
            "params": self.params,
            "peak_tflops": telemetry.peak_tflops(),
            "host_xfer_gbps": telemetry.host_xfer_gbps(),
            "mfu_prefill": self.mfu_prefill,
            "mfu_decode": self.mfu_decode,
            "prefill_ms_per_tok": round(self.prefill_s_per_tok * 1e3, 6),
            "decode_ms_per_tok": round(self.decode_s_per_tok * 1e3, 6),
            "swap_ms_per_tok": round(self.swap_s_per_tok * 1e3, 6),
            "kv_bytes_per_tok": self.kv_bytes_per_tok,
        }


# ---- synthetic replica --------------------------------------------------


@dataclasses.dataclass
class SimRequest:
    rid: int
    t_arrival: float
    tokens: list
    fps: list            # radix chain fingerprints of the full blocks
    max_new: int
    deadline_s: float
    family: int
    epoch: int = 0       # bumped on kill/re-place; stale events ignore
    promised: int = 0    # placement's promised cached tokens


class SimReplica:
    """A deterministic c-slot server with a real two-tier (HBM + host)
    radix-fingerprint prefix cache. Service times come from the cost
    model x a per-replica speed factor (hardware heterogeneity);
    a ``degraded`` replica runs DEGRADE_FACTOR slower with a stalled
    heartbeat — the slow-drip scenario's brownout, sized so a warm but
    browned-out replica's first token blows the hedge budget while its
    health checks keep answering ok."""

    DEGRADE_FACTOR = 20.0

    def __init__(self, name: str, cm: CostModel, *, slots: int,
                 block_size: int, digest_blocks: int, speed: float):
        self.name = name
        self.cm = cm
        self.block_size = block_size
        self.digest_cap = digest_blocks
        self.speed = speed
        self.slots = [0.0] * max(1, slots)
        self.hbm: OrderedDict = OrderedDict()   # fp -> None (LRU)
        self.host: OrderedDict = OrderedDict()  # evicted tier (LRU)
        self.digest_version = 0
        self.crashed = False
        self.draining = False
        self.degraded = False
        self.gen = 0          # crash epoch: stale completions ignore
        self.inflight: list = []  # [start, done, req]
        self.served = 0
        # Per-poll observation window (cleared every SLO poll): the
        # metrics fed to SloEngine come from completions since the
        # last poll, so burn reacts at poll cadence.
        self.window_ttft_ms: list = []
        self.window_good: list = []

    # -- cache ------------------------------------------------------------

    def digest_doc(self) -> dict:
        return {"version": self.digest_version,
                "block_size": self.block_size,
                "blocks": len(self.hbm),
                "fps": list(self.hbm),
                "host": {"fps": list(self.host),
                         "blocks": len(self.host)}}

    def insert_blocks(self, fps: list) -> None:
        for fp in fps:
            self.host.pop(fp, None)
            self.hbm[fp] = None
            self.hbm.move_to_end(fp)
        while len(self.hbm) > self.digest_cap:
            fp, _ = self.hbm.popitem(last=False)
            self.host[fp] = None
            self.host.move_to_end(fp)
        while len(self.host) > 2 * self.digest_cap:
            self.host.popitem(last=False)
        self.digest_version += 1

    # -- queue / service --------------------------------------------------

    def _prune(self, now: float) -> None:
        self.inflight = [e for e in self.inflight if e[1] > now]

    def queue_depth(self, now: float) -> int:
        self._prune(now)
        return sum(1 for s, _d, _r in self.inflight if s > now)

    def active(self, now: float) -> int:
        self._prune(now)
        return sum(1 for s, d, _r in self.inflight if s <= now < d)

    def beat_age_ms(self, now: float) -> float:
        return 10_000.0 if self.degraded else 50.0

    def healthz(self, now: float) -> dict:
        return {"ok": not self.crashed, "draining": self.draining,
                "beat_age_ms": self.beat_age_ms(now)}

    def price(self, req: SimRequest) -> tuple:
        """(service_s, first_token_s, cached_tokens): walk the request's
        chain fingerprints against the two-tier cache — HBM hits are
        free, host-tier hits pay the cheaper of the swap-in and
        recompute arms, the first miss ends the usable prefix (the
        chain rule digest_match_len enforces)."""
        bs = self.block_size
        hits = 0
        swap_blocks = 0
        for fp in req.fps:
            if fp in self.hbm:
                hits += 1
            elif fp in self.host:
                hits += 1
                swap_blocks += 1
            else:
                break
        cached = min(hits * bs, len(req.tokens) - 1)
        factor = self.speed * (self.DEGRADE_FACTOR if self.degraded
                               else 1.0)
        prefill_s = (len(req.tokens) - cached) \
            * self.cm.prefill_s_per_tok * factor
        # The preempt-cost arms: promote parked blocks at transfer
        # speed unless recompute is cheaper on this replica.
        swap_s = min(self.cm.swap_s_per_tok * factor,
                     self.cm.prefill_s_per_tok * factor) \
            * swap_blocks * bs
        decode_s = req.max_new * self.cm.decode_s_per_tok * factor
        first_token_s = prefill_s + swap_s
        return first_token_s + decode_s, first_token_s, cached

    def preview(self, now: float, service_s: float) -> tuple:
        """Earliest-free-slot admission WITHOUT committing: (slot,
        start, done, prev_busy_until) — hedging compares two previews
        and commits exactly one."""
        i = min(range(len(self.slots)), key=lambda j: (self.slots[j], j))
        start = max(now, self.slots[i])
        return i, start, start + service_s, self.slots[i]

    def commit(self, slot: int, done: float, start: float,
               req: SimRequest) -> None:
        self.slots[slot] = done
        self.inflight.append([start, done, req])

    def crash(self, now: float) -> list:
        """Kill the replica: every in-flight request dies; returns the
        casualties for the router-level failover classification."""
        self.crashed = True
        self.gen += 1
        self._prune(now)
        dead = [(s, d, r) for s, d, r in self.inflight]
        self.inflight = []
        self.slots = [now] * len(self.slots)
        return dead

    def recover(self) -> None:
        """Back, but COLD: the crash wiped HBM and the host tier."""
        self.crashed = False
        self.hbm.clear()
        self.host.clear()
        self.digest_version += 1


# ---- fleet + transport stubs -------------------------------------------


class SimFleet:
    """The synthetic replica set plus the stubbed scrape transport:
    ``serve_doc`` answers the three scrape endpoints from replica state
    (raising for a crashed replica — the breaker path's trigger)."""

    def __init__(self, cm: CostModel, clock: VirtualClock, rng, *,
                 slots: int, block_size: int, digest_blocks: int):
        self.cm = cm
        self.clock = clock
        self.rng = rng
        self.slots = slots
        self.block_size = block_size
        self.digest_blocks = digest_blocks
        self.replicas: OrderedDict = OrderedDict()
        self._next_idx = 0

    def spawn(self) -> SimReplica:
        name = f"sim-{self._next_idx:04d}"
        self._next_idx += 1
        rep = SimReplica(
            name, self.cm, slots=self.slots,
            block_size=self.block_size, digest_blocks=self.digest_blocks,
            speed=self.rng.uniform(0.9, 1.15))
        self.replicas[name] = rep
        return rep

    def serve_doc(self, replica: str, path: str) -> dict:
        rep = self.replicas.get(replica)
        if rep is None or rep.crashed:
            raise ConnectionError(f"{replica} unreachable")
        now = self.clock.t
        if path == "/healthz":
            return rep.healthz(now)
        if path == "/cachez":
            return {"digest": rep.digest_doc()}
        if path == "/poolz":
            return {"scheduler": {"queue_depth": rep.queue_depth(now)},
                    "pool": {"active": rep.active(now)}}
        raise ValueError(f"unknown scrape path {path}")


class SimRouter(FleetRouter):
    """The real router with ONLY the HTTP transport stubbed (the
    tools.mc move): scrape_once/_fold_scrape/_place/breakers/autoscale
    all run the production code paths against SimFleet documents. The
    constructor's listener socket is never served and is closed by the
    harness."""

    def __init__(self, fleet: SimFleet, **kwargs):
        self._fleet = fleet
        super().__init__(sorted(fleet.replicas), host="127.0.0.1",
                         port=0, **kwargs)

    def _fetch_json(self, replica: str, path: str):
        # Keep the production fault site live through the stub:
        # TPUBC_FAULT=router.scrape:... schedules compose with scenarios.
        faults.fire("router.scrape")
        return self._fleet.serve_doc(replica, path)


class SimScaleDriver:
    """The autoscale driver seam: scale-up spawns a cold synthetic
    replica, scale-down drains the youngest (placements route around
    it immediately; removal waits for its last in-flight completion —
    the LocalFleetDriver contract without the subprocess)."""

    def __init__(self, sim: "Simulation"):
        self.sim = sim

    def scale_to(self, n: int) -> None:
        sim = self.sim
        while True:
            live = [r for r in sim.fleet.replicas.values()
                    if not r.draining]
            if len(live) < n:
                rep = sim.fleet.spawn()
                sim.router.add_replica(rep.name)
                sim.note_scale("scale-up", len(live), len(live) + 1)
            elif len(live) > n:
                rep = live[-1]
                rep.draining = True
                sim.router.mark_draining(rep.name)
                last = max((d for _s, d, _r in rep.inflight),
                           default=0.0)
                sim.schedule(max(last, sim.clock.t) + 1e-6,
                             "drain-done", {"replica": rep.name})
                sim.note_scale("scale-down", len(live), len(live) - 1)
            else:
                return

    def stop(self) -> None:
        pass


# ---- trace replay -------------------------------------------------------


def load_trace(path: str) -> list:
    """A /requestz?format=jsonl capture -> normalized arrival list for
    the replay scenario: (dt_from_first_s, prompt_len, max_new,
    priority, deadline_s)."""
    out = []
    t0 = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            t_us = int(rec.get("t_arrival_us") or 0)
            if t0 is None:
                t0 = t_us
            deadline = rec.get("deadline")
            out.append({
                "dt_s": (t_us - t0) / 1e6,
                "prompt_len": int(rec.get("prompt_len") or 64),
                "max_new": int(rec.get("max_new") or 32),
                "priority": int(rec.get("priority") or 0),
                "deadline_s": (float(deadline) / 1e3
                               if isinstance(deadline, (int, float))
                               and deadline else 10.0),
            })
    return out


# ---- scenarios ----------------------------------------------------------


def _scenario_params(spec: SimSpec) -> dict:
    """Everything a scenario pins: arrival process, prompt shapes,
    fault schedule, SLO windows, autoscale config, phases. One place so
    a seed fully determines the run."""
    n = spec.replicas
    p = {
        "scrape_s": 5.0,
        "poll_s": 5.0,
        "stale_s": 15.0,
        "breaker_s": 2.0,
        "hedge_s": 0.25,
        "retries": 2,
        "windows": (60.0, 300.0),
        "families": 16,
        "prefix_blocks": 4,
        "suffix_tokens": 12,
        "max_new": 32,
        "deadline_s": 10.0,
        "hot_family_share": None,   # (t_from, family, share)
        "faults": [],               # [(t, kind, payload)]
        "autoscale": None,          # (min, max) or None
        "duration_s": 300.0,
        "rate": None,               # fn(t) -> arrivals/s
        "phases": [],
    }
    if spec.scenario == "diurnal":
        dur = spec.duration_s or 300.0
        base, peak = 0.004 * n, 0.02 * n

        def rate(t, _b=base, _p=peak, _d=dur):
            wave = 0.5 * (1.0 + math.sin(2 * math.pi * t / (_d / 2)
                                         - math.pi / 2))
            return _b + _p * wave * wave

        p.update(duration_s=dur, rate=rate,
                 autoscale=(max(1, n // 2), n),
                 phases=[("wave-1", 0.0, dur / 2),
                         ("wave-2", dur / 2, dur)])
    elif spec.scenario == "hot-prefix":
        dur = spec.duration_s or 240.0
        r = max(4.0, 0.01 * n)
        p.update(duration_s=dur, rate=lambda t, _r=r: _r,
                 families=32,
                 hot_family_share=(dur / 2, 0, 0.8),
                 phases=[("uniform", 0.0, dur / 2),
                         ("storm", dur / 2, dur)])
    elif spec.scenario == "crash-cascade":
        dur = spec.duration_s or 240.0
        r = max(4.0, 0.01 * n)
        t_crash = dur / 3
        k = max(1, n // 5)
        flts = [(t_crash + 0.2 * i, "crash", {"idx": i})
                for i in range(k)]
        flts += [(t_crash + 30.0 + 0.2 * i, "recover", {"idx": i})
                 for i in range(k)]
        p.update(duration_s=dur, rate=lambda t, _r=r: _r, faults=flts,
                 phases=[("steady", 0.0, t_crash),
                         ("cascade", t_crash, t_crash + 30.0),
                         ("recovery", t_crash + 30.0, dur)])
    elif spec.scenario == "slow-drip":
        dur = spec.duration_s or 240.0
        r = max(4.0, 0.008 * n)
        drip = [(20.0 * (i + 1), "degrade", {"idx": i})
                for i in range(min(n, int(dur // 20) - 1))]
        # Long shared prefixes: cache affinity keeps sending traffic
        # to the browned-out replicas it warmed, so the run shows
        # whether the hedge gate (stalled beat + blown first-token
        # budget) actually rescues those requests.
        p.update(duration_s=dur, rate=lambda t, _r=r: _r, faults=drip,
                 prefix_blocks=16, max_new=128,
                 phases=[("drip", 0.0, dur)])
    elif spec.scenario == "limit-cycle":
        dur = spec.duration_s or 240.0
        # Pinned at the 2 <-> 3 replica capacity boundary: ~1s service
        # (decode-heavy), 8 slots/replica, 20 req/s offered = 2.5
        # replicas' worth. Under-provisioned, queue wait crosses the
        # ttft objective within a few polls; over-provisioned, the
        # short burn window goes quiet just as fast. The default
        # streak/cooldown trio damps that into a slow drift; the
        # planted bug turns it into a poll-cadence flap the
        # autoscale-limit-cycle invariant catches.
        # Cache-NEUTRAL prompts (no shared prefix -> every score is 0
        # -> pure least-load spread): this scenario studies autoscale
        # dynamics, and cache-affinity herding would mask them.
        p.update(duration_s=dur, max_new=1600, deadline_s=30.0,
                 windows=(10.0,), poll_s=5.0,
                 rate=lambda t: 20.0,
                 families=1, prefix_blocks=0, suffix_tokens=12,
                 autoscale=(1, max(8, min(16, n))),
                 phases=[("steady", 0.0, dur)])
    elif spec.scenario == "replay":
        if not spec.trace:
            raise ValueError("scenario 'replay' needs --replay-trace")
        arrivals = load_trace(spec.trace)
        dur = (arrivals[-1]["dt_s"] + 10.0) if arrivals else 10.0
        p.update(duration_s=spec.duration_s or dur, trace=arrivals,
                 phases=[("replay", 0.0, dur)])
    else:
        raise ValueError(f"unknown scenario {spec.scenario!r}")
    return p


# ---- the simulation -----------------------------------------------------


class Simulation:
    """One deterministic run: a heap of (t, seq, kind) events driving
    arrivals, completions, scrapes, SLO polls, faults, and scale
    actions against the real policy objects on the virtual clock."""

    def __init__(self, spec: SimSpec):
        self.spec = spec
        self.params = _scenario_params(spec)
        self.rng = random.Random(spec.seed)
        self.clock = VirtualClock()
        self.cm = CostModel()
        self.block_size = _env_int("TPUBC_SIM_BLOCK_SIZE", 16)
        self.fleet = SimFleet(
            self.cm, self.clock, self.rng,
            slots=_env_int("TPUBC_SIM_SLOTS", 8),
            block_size=self.block_size,
            digest_blocks=_env_int("TPUBC_SIM_DIGEST_BLOCKS", 256))
        start_n = spec.replicas
        if spec.scenario == "limit-cycle":
            start_n = min(spec.replicas, 2)
        for _ in range(start_n):
            self.fleet.spawn()
        autoscaler = None
        if self.params["autoscale"] is not None:
            lo, hi = self.params["autoscale"]
            if spec.bug == "limit-cycle":
                # The planted bug: the flap-damping trio disabled —
                # 1-tick streaks, zero cooldown. The limit-cycle
                # invariant must catch the oscillation this causes.
                autoscaler = AutoscaleController(
                    lo, hi, up_ticks=1, down_ticks=1, cooldown_s=0.0)
            else:
                autoscaler = AutoscaleController(lo, hi)
        self.driver = SimScaleDriver(self)
        self.router = SimRouter(
            self.fleet,
            scrape_s=self.params["scrape_s"],
            stale_s=self.params["stale_s"],
            breaker_s=self.params["breaker_s"],
            hedge_s=self.params["hedge_s"],
            retries=self.params["retries"],
            autoscaler=autoscaler,
            driver=self.driver if autoscaler is not None else None)
        self.engine = SloEngine(windows=self.params["windows"], ring=32)
        # Prompt families: each a pinned random prefix of full blocks;
        # the per-family chain fps are memoized (identical prefix ->
        # identical radix chain, so one hash walk serves every reuse).
        self._families = []
        for _ in range(self.params["families"]):
            toks = [self.rng.randrange(2, 32000)
                    for _ in range(self.params["prefix_blocks"]
                                   * self.block_size)]
            self._families.append(toks)
        self._family_fps = {}
        # Event heap + accounting.
        self._heap: list = []
        self._seq = 0
        self._rid = 0
        self.violations: list = []
        self.scale_events: list = []
        self.stats = {
            "arrivals": 0, "served": 0, "good": 0,
            "failed_midstream": 0, "unroutable": 0,
            "failovers": 0, "hedges": 0, "misroutes": 0,
            "placements": 0, "route_hits": 0, "degraded_placements": 0,
            "breaker_open_events": 0, "swapin_blocks": 0,
        }
        self._open_breakers: set = set()
        self._phase_stats = {name: {"arrivals": 0, "served": 0,
                                    "good": 0, "route_hits": 0,
                                    "placements": 0}
                             for name, _a, _b in self.params["phases"]}
        self._trace_events: list = []

    # -- plumbing ---------------------------------------------------------

    def schedule(self, t: float, kind: str, payload: dict) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, payload))

    def note_scale(self, action: str, cur: int, target: int) -> None:
        self.scale_events.append({"t": round(self.clock.t, 6),
                                  "action": action,
                                  "from": cur, "to": target})
        self._trace_events.append({
            "name": f"{action} {cur}->{target}", "ph": "i",
            "ts": int(self.clock.t * 1e6), "pid": 0, "tid": 1,
            "cat": "autoscale", "s": "g"})

    def _phase_of(self, t: float):
        for name, a, b in self.params["phases"]:
            if a <= t < b:
                return self._phase_stats[name]
        return None

    def _chain_fps(self, tokens: list, family: int) -> list:
        """The radix chain for a prompt = memoized family-prefix chain
        + freshly hashed unique-suffix blocks (same block_hash chain
        the engine's prefix cache keys on)."""
        bs = self.block_size
        pb = self.params["prefix_blocks"]
        pre = self._family_fps.get(family)
        if pre is None:
            key = b""
            fps = []
            for j in range(pb):
                key = block_hash(key, tokens[j * bs:(j + 1) * bs])
                fps.append(key_fingerprint(key))
            pre = self._family_fps[family] = (fps, key)
        fps, key = list(pre[0]), pre[1]
        for j in range(pb, len(tokens) // bs):
            key = block_hash(key, tokens[j * bs:(j + 1) * bs])
            fps.append(key_fingerprint(key))
        return fps

    def _mk_request(self, now: float) -> SimRequest:
        hot = self.params["hot_family_share"]
        nfam = self.params["families"]
        if hot is not None and now >= hot[0] \
                and self.rng.random() < hot[2]:
            family = hot[1]
        else:
            family = self.rng.randrange(nfam)
        tokens = list(self._families[family])
        tokens += [self.rng.randrange(2, 32000)
                   for _ in range(self.params["suffix_tokens"])]
        self._rid += 1
        return SimRequest(
            rid=self._rid, t_arrival=now, tokens=tokens,
            fps=self._chain_fps(tokens, family),
            max_new=self.params["max_new"],
            deadline_s=self.params["deadline_s"], family=family)

    # -- dispatch (the synthetic data plane) ------------------------------

    def _dispatch(self, req: SimRequest, exclude: set,
                  failover_budget: int) -> None:
        """Place via the REAL _place, admit on the synthetic replica,
        hedge through the real beat-stall gate, fail over through the
        real breaker bookkeeping."""
        now = self.clock.t
        placement = self.router._place(req.tokens, exclude=exclude)
        if placement is None:
            self.stats["unroutable"] += 1
            return
        name, promised, degraded = placement
        st = self.router._replicas.get(name)
        # Pure read: allow() would transition open -> half-open itself.
        if st is not None and st["breaker"].state == "open" \
                and now < st["breaker"].open_until:
            self._violate("breaker-open-dispatch",
                          f"placement chose {name} with an open breaker")
        self.stats["placements"] += 1
        ph = self._phase_of(req.t_arrival)
        if ph is not None:
            ph["placements"] += 1
        if degraded:
            self.stats["degraded_placements"] += 1
        if promised > 0:
            self.stats["route_hits"] += 1
            if ph is not None:
                ph["route_hits"] += 1
        req.promised = promised
        rep = self.fleet.replicas.get(name)
        try:
            faults.fire("sim.dispatch")
            if rep is None or rep.crashed:
                raise ConnectionError(f"{name} unreachable")
        except Exception as e:  # noqa: BLE001 - dispatch death
            self.router._breaker_fail(name, f"{type(e).__name__}: {e}")
            if failover_budget > 0:
                self.stats["failovers"] += 1
                self._dispatch(req, exclude | {name},
                               failover_budget - 1)
            else:
                self.stats["unroutable"] += 1
            return
        service_s, first_s, cached = rep.price(req)
        slot, start, done, _prev = rep.preview(now, service_s)
        # The hedge gate, exactly as the proxy runs it: no first token
        # within hedge_s AND a stalled heartbeat on the scraped state.
        est_ttft = (start - now) + first_s
        if est_ttft > self.router.hedge_s \
                and self.router._beat_stalled(name):
            alt = self.router._place(req.tokens, exclude=exclude | {name})
            if alt is not None:
                alt_rep = self.fleet.replicas.get(alt[0])
                if alt_rep is not None and not alt_rep.crashed:
                    a_service, a_first, a_cached = alt_rep.price(req)
                    a_slot, a_start, a_done, _p = alt_rep.preview(
                        now, a_service)
                    self.stats["hedges"] += 1
                    if (a_start - now) + a_first < est_ttft:
                        rep, name = alt_rep, alt[0]
                        slot, start, done = a_slot, a_start, a_done
                        service_s, first_s, cached = (a_service, a_first,
                                                      a_cached)
                        req.promised = alt[1]
        rep.commit(slot, done, start, req)
        # Mirror _route's dispatch bookkeeping: st["inflight"] is the
        # router's own between-scrapes load correction, and placement
        # herds onto one replica without it.
        st = self.router._replicas.get(name)
        if st is not None:
            st["inflight"] += 1
            st["dispatches"] += 1
        self.schedule(done, "complete", {
            "replica": name, "rid": req.rid, "req": req,
            "epoch": req.epoch, "gen": rep.gen,
            "ttft_s": (start - now) + first_s, "cached": cached})

    def _violate(self, invariant: str, detail: str) -> None:
        self.violations.append(Violation(invariant, detail, self.spec))

    # -- event handlers ---------------------------------------------------

    def _on_arrive(self, payload: dict) -> None:
        now = self.clock.t
        self.stats["arrivals"] += 1
        ph = self._phase_of(now)
        if ph is not None:
            ph["arrivals"] += 1
        req = payload.get("req") or self._mk_request(now)
        self._dispatch(req, set(), self.router.retries)
        # Schedule the next arrival (open-loop arrival process).
        if "req" not in payload:
            rate = self.params["rate"](now)
            if rate > 1e-9:
                dt = self.rng.expovariate(rate)
                t_next = now + dt
                if t_next < self.params["duration_s"]:
                    self.schedule(t_next, "arrive", {})
            else:
                self.schedule(now + 1.0, "arrive", {})

    def _on_complete(self, payload: dict) -> None:
        req: SimRequest = payload["req"]
        rep = self.fleet.replicas.get(payload["replica"])
        if rep is None or payload["gen"] != rep.gen \
                or payload["epoch"] != req.epoch:
            return  # killed by a crash, or re-placed: stale event
        st = self.router._replicas.get(payload["replica"])
        if st is not None:
            st["inflight"] = max(0, st["inflight"] - 1)
        now = self.clock.t
        rep.served += 1
        self.stats["served"] += 1
        rep.insert_blocks(req.fps)
        total_s = now - req.t_arrival
        good = total_s <= req.deadline_s
        if good:
            self.stats["good"] += 1
        ph = self._phase_of(req.t_arrival)
        if ph is not None:
            ph["served"] += 1
            if good:
                ph["good"] += 1
        rep.window_ttft_ms.append(payload["ttft_s"] * 1e3)
        rep.window_good.append(good)
        # The production misroute check: stale digests that promised
        # blocks the replica no longer held are counted, not errored.
        self.router._misroute_check(rep.name, req.promised,
                                    payload["cached"])
        if req.promised > 0 and payload["cached"] < req.promised:
            self.stats["misroutes"] += 1

    def _on_scrape(self, _payload: dict) -> None:
        now = self.clock.t
        self.router.scrape_once(now=now)
        open_now = {r for r, st in self.router._replicas.items()
                    if st["breaker"].state == "open"}
        self.stats["breaker_open_events"] += len(
            open_now - self._open_breakers)
        self._open_breakers = open_now
        if now + self.params["scrape_s"] < self.params["duration_s"]:
            self.schedule(now + self.params["scrape_s"], "scrape", {})

    def _on_poll(self, _payload: dict) -> None:
        """One SLO tick: feed the engine per-replica observations from
        the window since the last poll, evaluate burn, drive the real
        autoscale path off the burn document."""
        now = self.clock.t
        for rep in self.fleet.replicas.values():
            if rep.crashed:
                continue
            # The engine samples by /metrics.json KEY (obj.key), so the
            # twin publishes the same metric names a live replica does.
            m: dict = {"serve_queue_depth": rep.queue_depth(now)}
            if rep.window_ttft_ms:
                s = sorted(rep.window_ttft_ms)
                m["serve_ttft_ms_p99"] = s[min(len(s) - 1,
                                               int(0.99 * (len(s) - 1)))]
            if rep.window_good:
                m["serve_admitted_ratio"] = (
                    sum(1 for g in rep.window_good if g)
                    / len(rep.window_good))
            rep.window_ttft_ms = []
            rep.window_good = []
            self.engine.record(rep.name, m, t=now)
        burn = self.engine.evaluate(now=now)
        if self.router.autoscaler is not None:
            self.router.autoscale_once(burn=burn, now=now)
        if now + self.params["poll_s"] < self.params["duration_s"]:
            self.schedule(now + self.params["poll_s"], "poll", {})

    def _on_fault(self, payload: dict) -> None:
        kind = payload["kind"]
        names = sorted(self.fleet.replicas)
        idx = payload["idx"] % max(1, len(names))
        rep = self.fleet.replicas[names[idx]]
        now = self.clock.t
        self._trace_events.append({
            "name": f"{kind} {rep.name}", "ph": "i",
            "ts": int(now * 1e6), "pid": 0, "tid": 2, "cat": "fault",
            "s": "g"})
        if kind == "crash":
            casualties = rep.crash(now)
            st = self.router._replicas.get(rep.name)
            if st is not None:
                st["inflight"] = max(0, st["inflight"]
                                     - len(casualties))
            for start, _done, req in casualties:
                req.epoch += 1
                first_s = rep.price(req)[1]
                if now < start + first_s:
                    # Pre-first-token: the real state machine re-places
                    # on survivors silently.
                    self.router._breaker_fail(rep.name,
                                              "replica crashed")
                    self.stats["failovers"] += 1
                    self._dispatch(req, {rep.name},
                                   self.router.retries - 1)
                else:
                    # Mid-stream: exactly-one-terminal-outcome says a
                    # terminal failover error, never a re-run.
                    self.stats["failed_midstream"] += 1
        elif kind == "recover":
            rep.recover()
        elif kind == "degrade":
            rep.degraded = True

    def _on_drain_done(self, payload: dict) -> None:
        name = payload["replica"]
        rep = self.fleet.replicas.get(name)
        if rep is None or not rep.draining:
            return
        self.router.remove_replica(name)
        del self.fleet.replicas[name]

    # -- run + report -----------------------------------------------------

    def run(self) -> dict:
        telemetry.set_clock(self.clock)
        try:
            self.schedule(0.0, "scrape", {})
            self.schedule(self.params["poll_s"], "poll", {})
            self.schedule(0.0, "arrive", {})
            for t, kind, payload in self.params["faults"]:
                self.schedule(t, "fault", dict(payload, kind=kind))
            if self.params.get("trace") is not None:
                # Replay mode: the recorded arrivals ARE the process.
                self._heap = [e for e in self._heap if e[2] != "arrive"]
                heapq.heapify(self._heap)
                for a in self.params["trace"]:
                    req_tokens_len = max(self.block_size,
                                         a["prompt_len"])
                    fam = a["prompt_len"] % self.params["families"]
                    tokens = list(self._families[fam])
                    extra = req_tokens_len - len(tokens)
                    if extra > 0:
                        tokens += [self.rng.randrange(2, 32000)
                                   for _ in range(extra)]
                    else:
                        tokens = tokens[:req_tokens_len]
                    self._rid += 1
                    req = SimRequest(
                        rid=self._rid, t_arrival=a["dt_s"],
                        tokens=tokens,
                        fps=self._chain_fps_raw(tokens),
                        max_new=a["max_new"],
                        deadline_s=a["deadline_s"], family=fam)
                    self.schedule(a["dt_s"], "arrive", {"req": req})
            handlers = {"arrive": self._on_arrive,
                        "complete": self._on_complete,
                        "scrape": self._on_scrape,
                        "poll": self._on_poll,
                        "fault": self._on_fault,
                        "drain-done": self._on_drain_done}
            # Arrivals stop at duration_s by construction, so the heap
            # drains to empty: every admitted request reaches a
            # terminal outcome (the accounting invariant's premise).
            # The hard cap only guards against a harness bug looping.
            hard_stop = self.params["duration_s"] + 86_400.0
            while self._heap:
                t, _seq, kind, payload = heapq.heappop(self._heap)
                if t > hard_stop:
                    raise RuntimeError(
                        f"event at t={t:.1f}s past the hard stop — "
                        f"the event loop is not draining")
                self.clock.advance_to(t)
                handlers[kind](payload)
            self._check_end_invariants()
            return self._report()
        finally:
            telemetry.set_clock(None)
            self.router.httpd.server_close()

    def _chain_fps_raw(self, tokens: list) -> list:
        key = b""
        fps = []
        for j in range(len(tokens) // self.block_size):
            key = block_hash(
                key, tokens[j * self.block_size:
                            (j + 1) * self.block_size])
            fps.append(key_fingerprint(key))
        return fps

    def _check_end_invariants(self) -> None:
        s = self.stats
        accounted = (s["served"] + s["failed_midstream"]
                     + s["unroutable"])
        if accounted != s["arrivals"]:
            self._violate(
                "request-accounting",
                f"{s['arrivals']} arrivals but {accounted} terminal "
                f"outcomes (served {s['served']} + midstream "
                f"{s['failed_midstream']} + unroutable "
                f"{s['unroutable']})")
        # Autoscale limit-cycle detector: many scale actions with ~zero
        # net fleet change inside one sliding window is churn without
        # progress. The flap-damping trio bounds a healthy controller
        # to cooldown_s-spaced actions (<= 4 per 120s window), so the
        # churn threshold below is unreachable unless damping is off —
        # which is exactly the planted bug.
        window, min_events, max_net = 120.0, 8, 2
        ev = self.scale_events
        for i in range(len(ev)):
            j = i
            while j + 1 < len(ev) and ev[j + 1]["t"] - ev[i]["t"] \
                    <= window:
                j += 1
            n_ev = j - i + 1
            net = ev[j]["to"] - ev[i]["from"]
            if n_ev >= min_events and abs(net) <= max_net:
                self._violate(
                    "autoscale-limit-cycle",
                    f"{n_ev} scale actions with net fleet change "
                    f"{net:+d} within {window:.0f}s "
                    f"(t={ev[i]['t']:.1f}s...{ev[j]['t']:.1f}s) — "
                    f"the controller is churning in a limit cycle, "
                    f"not converging")
                break

    def _report(self) -> dict:
        s = self.stats
        served = max(1, s["served"])
        placements = max(1, s["placements"])
        per_phase = {}
        for name, a, b in self.params["phases"]:
            st = self._phase_stats[name]
            per_phase[name] = {
                "window_s": [round(a, 3), round(b, 3)],
                "arrivals": st["arrivals"],
                "served": st["served"],
                "slo_attainment": round(
                    st["good"] / max(1, st["served"]), 6),
                "route_hit_frac": round(
                    st["route_hits"] / max(1, st["placements"]), 6),
            }
        report = {
            "sim": {
                "scenario": self.spec.scenario,
                "seed": self.spec.seed,
                "seed_str": self.spec.seed_str(),
                "bug": self.spec.bug,
                "replicas_initial": self.spec.replicas,
                "replicas_final": len(self.fleet.replicas),
                "virtual_duration_s": round(
                    self.params["duration_s"], 3),
            },
            "cost_model": self.cm.provenance(),
            "traffic": {
                "arrivals": s["arrivals"],
                "served": s["served"],
                "good": s["good"],
                "failed_midstream": s["failed_midstream"],
                "unroutable": s["unroutable"],
                "failovers": s["failovers"],
                "hedges": s["hedges"],
                "misroutes": s["misroutes"],
            },
            "slo_attainment": round(s["good"] / served, 6),
            "goodput_frac": round(s["good"] / max(1, s["arrivals"]), 6),
            "route_hit_frac": round(s["route_hits"] / placements, 6),
            "degraded_placements": s["degraded_placements"],
            "breaker_open_events": s["breaker_open_events"],
            "scale_events": self.scale_events,
            "alerts": self.engine.alerts(),
            "per_phase": per_phase,
            "violations": [{"invariant": v.invariant,
                            "detail": v.detail, "seed": v.seed()}
                           for v in self.violations],
        }
        return report

    def chrome_trace(self) -> dict:
        """The per-phase timeline of the simulated fleet, Chrome
        trace-event JSON (Perfetto-loadable): phase spans, scale/fault
        instants, alert transitions."""
        events = [{"name": "process_name", "ph": "M", "pid": 0,
                   "args": {"name": f"tools.sim {self.spec.seed_str()}"}}]
        for name, a, b in self.params["phases"]:
            events.append({"name": f"phase:{name}", "ph": "X",
                           "ts": int(a * 1e6),
                           "dur": int((b - a) * 1e6),
                           "pid": 0, "tid": 0, "cat": "phase"})
        events.extend(self._trace_events)
        for tr in self.engine.alerts()["transitions"]:
            events.append({"name": f"{tr['event']}:{tr['slo']}"
                                   f"@{tr['replica']}",
                           "ph": "i", "ts": tr["t_us"], "pid": 0,
                           "tid": 3, "cat": "slo", "s": "g"})
        return {"displayTimeUnit": "ms", "traceEvents": events}


def run(spec: SimSpec) -> tuple:
    """Run one spec; returns (report, violations, sim)."""
    sim = Simulation(spec)
    report = sim.run()
    return report, sim.violations, sim


def report_bytes(report: dict) -> bytes:
    """THE byte-identity surface: same seed -> same bytes, asserted by
    the CI determinism check."""
    return (json.dumps(report, sort_keys=True, indent=1) + "\n").encode()
