"""tools.sim — the fleet digital twin.

A discrete-event simulator that runs the REAL control-plane policy
objects (router placement/breakers/hedging/failover, the autoscale
hysteresis, the SLO burn engine) against synthetic replicas priced by
the repo's measured cost models, on one injected virtual clock.
``python -m tools.sim --scenario diurnal --replicas 1000 --seed 42``
plays a 1000-replica day in CI seconds; every violated invariant
prints a standalone replay seed. See harness.py for the full story.
"""

from tools.sim.harness import (BUGS, SCENARIOS, CostModel, SimSpec,
                               Simulation, Violation, load_trace,
                               parse_seed, report_bytes, run)

__all__ = ["BUGS", "SCENARIOS", "CostModel", "SimSpec", "Simulation",
           "Violation", "load_trace", "parse_seed", "report_bytes",
           "run"]
