"""CLI: ``python -m tools.sim --scenario diurnal --replicas 1000`` runs
one scenario, ``--replay '<seed>'`` re-runs a printed violation seed,
``--seed-bug limit-cycle`` demonstrates the seeded autoscaler bug end
to end (find it, print the seed, reproduce it from that seed alone).

Exit codes follow tools.mc: 0 clean, 1 violation(s), 2 usage error.
"""

import argparse
import json
import sys
import time


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m tools.sim",
        description="Discrete-event fleet simulator driving the real "
                    "router/autoscaler/SLO policy objects on a virtual "
                    "clock.")
    p.add_argument("--scenario", default="diurnal",
                   help="one of: diurnal, hot-prefix, crash-cascade, "
                        "slow-drip, limit-cycle, replay")
    p.add_argument("--replicas", type=int, default=100,
                   help="fleet size (default 100; CI pins 1000)")
    p.add_argument("--seed", type=int, default=0,
                   help="the run is a pure function of "
                        "(scenario, replicas, seed)")
    p.add_argument("--duration", type=float, default=None,
                   help="override the scenario's virtual duration (s)")
    p.add_argument("--seed-bug", choices=("limit-cycle",), default=None,
                   help="arm the seeded autoscaler bug (demo/CI "
                        "fixture: the run must FIND it and reproduce "
                        "it from its own printed seed)")
    p.add_argument("--replay", default=None, metavar="SEED",
                   help="re-run one printed violation seed "
                        "(scenario:rN:sN[:dSECS][:bug=NAME]) instead "
                        "of taking the flags above")
    p.add_argument("--replay-trace", default=None, metavar="PATH",
                   help="a /requestz?format=jsonl capture to replay as "
                        "the arrival process (scenario 'replay')")
    p.add_argument("--report-out", default=None, metavar="PATH",
                   help="write the deterministic report JSON to PATH "
                        "(byte-identical for a fixed seed)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event timeline to PATH")
    p.add_argument("--violation-out", default=None, metavar="PATH",
                   help="write the first violation (with its replay "
                        "seed) to PATH (CI uploads it as an artifact)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    from tools.sim import (SCENARIOS, SimSpec, parse_seed, report_bytes,
                           run)

    if args.replay is not None:
        try:
            spec = parse_seed(args.replay)
        except ValueError as e:
            print(f"tools.sim: {e}", file=sys.stderr)
            return 2
        spec.trace = args.replay_trace
    else:
        if args.scenario not in SCENARIOS:
            print(f"tools.sim: unknown scenario {args.scenario!r} "
                  f"(known: {', '.join(SCENARIOS)})", file=sys.stderr)
            return 2
        spec = SimSpec(scenario=args.scenario, replicas=args.replicas,
                       seed=args.seed, bug=args.seed_bug,
                       duration_s=args.duration,
                       trace=args.replay_trace)

    t0 = time.monotonic()
    try:
        report, violations, sim = run(spec)
    except ValueError as e:
        print(f"tools.sim: {e}", file=sys.stderr)
        return 2
    dt = time.monotonic() - t0

    if args.report_out:
        with open(args.report_out, "wb") as f:
            f.write(report_bytes(report))
    if args.trace_out:
        with open(args.trace_out, "w", encoding="utf-8") as f:
            json.dump(sim.chrome_trace(), f)
            f.write("\n")
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        t = report["traffic"]
        print(f"tools.sim: {spec.seed_str()} — "
              f"{report['sim']['virtual_duration_s']:.0f} virtual s, "
              f"{t['arrivals']} arrivals over "
              f"{report['sim']['replicas_final']} replicas in {dt:.1f}s "
              f"wall")
        print(f"tools.sim: slo_attainment="
              f"{report['slo_attainment']:.4f} "
              f"goodput_frac={report['goodput_frac']:.4f} "
              f"route_hit_frac={report['route_hit_frac']:.4f} "
              f"scale_events={len(report['scale_events'])} "
              f"hedges={t['hedges']} failovers={t['failovers']} "
              f"unroutable={t['unroutable']}")

    if not violations:
        if not args.json:
            print("tools.sim: scenario completed with every invariant "
                  "intact")
        return 0

    v = violations[0]
    seed = v.seed()
    print(f"tools.sim: VIOLATION [{v.invariant}] {v.detail}")
    print(f"tools.sim: replay with: python -m tools.sim "
          f"--replay '{seed}'")
    if args.violation_out:
        with open(args.violation_out, "w", encoding="utf-8") as f:
            f.write(json.dumps({"invariant": v.invariant,
                                "detail": v.detail, "seed": seed,
                                "seed_bug": spec.bug}, indent=2) + "\n")
        print(f"tools.sim: violation written to {args.violation_out}")
    # The seeded-bug demo must close the loop: the printed seed ALONE
    # (parsed back through the grammar, not the in-memory spec) must
    # reproduce the violation from scratch.
    if args.seed_bug and args.replay is None:
        _rep2, viols2, _sim2 = run(parse_seed(seed))
        ok = any(w.invariant == v.invariant for w in viols2)
        print("tools.sim: seed replay "
              + ("REPRODUCED the violation" if ok
                 else "FAILED to reproduce (nondeterminism bug!)"))
    return 1


if __name__ == "__main__":
    sys.exit(main())
