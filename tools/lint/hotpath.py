"""JAX hot-path purity pass.

Two kinds of hot code:

* JIT-REACHABLE: functions decorated ``@jax.jit`` / ``@partial(jax.jit,
  ...)`` (or wrapped ``jax.jit(f)``), plus everything they call,
  resolved through the scanned package's imports — the code that runs
  under trace.  Host-device syncs there either fail under jit or
  silently force a device round-trip per trace; impure reads bake a
  trace-time value into the compiled program (the classic "time.time()
  under jit returns the compile-time clock" bug).
* HOT LOOPS: the serving decode/step/verify host loops (configured in
  ``HOT_LOOPS``).  They legally sync with the device, but each sync is a
  per-round stall — so every one must be DELIBERATE: either allowlisted
  in tools/lint/allowlist.txt (the retirement folds, host-side ngram
  drafting) or flagged.  Hot-loop checking is per-body (not transitive):
  the loop's own statements are the round's critical path.

Rules:

* ``jit-host-sync``  — ``.item()``, ``np.asarray``/``np.array``,
  ``jax.device_get``, ``jax.block_until_ready`` in jit-reachable code.
* ``jit-impure``     — ``time.*()`` clock reads, ``os.environ``,
  ``random.*`` in jit-reachable code (trace-time constants).
* ``jit-scalar-cast``— ``float()/int()/bool()`` on a non-literal in
  jit-reachable code (forces a concrete value out of a tracer).
* ``hot-loop-sync``  — the sync calls above inside a configured hot
  loop's own body.
* ``static-by-keyword`` — a call to a jit function passing one of its
  ``static_argnames`` POSITIONALLY (this repo pins statics-by-keyword:
  see workload/decode.py's generate; a positional static silently
  retraces per value or fails, depending on the jax version).

``isinstance(x, jax.core.Tracer)``-guarded ``if`` statements are skipped
entirely (both branches): that idiom is exactly how eager-only code
excludes itself from the trace.
"""

from __future__ import annotations

import ast

from . import Finding, allowed

# The serving/scheduling host loops whose per-round syncs must be
# deliberate.  module-dotted-suffix -> qualnames.
HOT_LOOPS = {
    "tpu_bootstrap.workload.serving": (
        "SlotPool.step_round", "SlotPool._decode_round",
        "SlotPool._speculative_round",
        "ResidentPool.step_round", "ResidentPool._spec_round",
        "PagedPool.step_round", "PagedPool._spec_round",
        "PagedPool._prefill_phase",
        "PagedPool._host_fetch", "PagedPool._host_restore",
        "Scheduler.step",
    ),
}

SYNC_ATTR_CALLS = {"item"}
IMPURE_TIME = {"time", "monotonic", "monotonic_ns", "perf_counter",
               "perf_counter_ns", "time_ns"}
SCALAR_CASTS = {"float", "int", "bool"}


def _module_name(rel: str) -> str:
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class ModuleInfo:
    def __init__(self, src):
        self.src = src
        self.name = _module_name(src.rel)
        self.functions: dict = {}     # qualname -> FunctionDef
        self.classes: dict = {}       # name -> ClassDef
        self.import_aliases: dict = {}   # local name -> dotted module
        self.from_imports: dict = {}     # local name -> (module, name)
        self.np_aliases: set = set()     # names bound to the numpy module
        self.jit_info: dict = {}      # qualname -> {params, statics}
        self._collect()

    def _collect(self) -> None:
        for node in self.src.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.import_aliases[local] = a.name
                    if a.name == "numpy":
                        self.np_aliases.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (
                        node.module, a.name)
            elif isinstance(node, ast.FunctionDef):
                self.functions[node.name] = node
                self._collect_nested(node, node.name)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        qual = f"{node.name}.{item.name}"
                        self.functions[qual] = item
                        self._collect_nested(item, qual)

    def _collect_nested(self, fn: ast.FunctionDef, outer: str) -> None:
        """Nested defs (the train/distill `step` closures that get
        jax.jit-wrapped at the call site) register under a qualified
        name, plus the bare name when it does not clash."""
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                self.functions.setdefault(f"{outer}.<locals>.{node.name}",
                                          node)
                self.functions.setdefault(node.name, node)

    def jit_roots(self) -> set:
        roots = set()
        for qual, fn in self.functions.items():
            info = _jit_decoration(fn)
            if info is not None:
                self.jit_info[qual] = info
                roots.add(qual)
        # x = jax.jit(f) / jax.jit(f, ...) at module or function level.
        for node in ast.walk(self.src.tree):
            if (isinstance(node, ast.Call)
                    and _dotted(node.func) in ("jax.jit", "jit")
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in self.functions):
                roots.add(node.args[0].id)
        return roots


def _jit_decoration(fn: ast.FunctionDef) -> dict | None:
    """{'params': [...], 'statics': {...}} when fn is jit-decorated."""
    for dec in fn.decorator_list:
        target, statics = None, set()
        if _dotted(dec) in ("jax.jit", "jit"):
            target = dec
        elif isinstance(dec, ast.Call):
            name = _dotted(dec.func)
            if name in ("jax.jit", "jit"):
                target = dec
            elif name.endswith("partial") and dec.args and _dotted(
                    dec.args[0]) in ("jax.jit", "jit"):
                target = dec
            if target is not None:
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        for el in ast.walk(kw.value):
                            if (isinstance(el, ast.Constant)
                                    and isinstance(el.value, str)):
                                statics.add(el.value)
        if target is not None:
            params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
            return {"params": params, "statics": statics}
    return None


def _is_tracer_guard(node: ast.If) -> bool:
    for sub in ast.walk(node.test):
        if isinstance(sub, ast.Attribute) and sub.attr == "Tracer":
            return True
        if isinstance(sub, ast.Name) and sub.id in ("tracing",
                                                    "interpret"):
            return True
    return False


class _HotChecker(ast.NodeVisitor):
    def __init__(self, pass_ctx, mod: ModuleInfo, qual: str,
                 fn: ast.FunctionDef, *, mode: str):
        self.ctx = pass_ctx
        self.mod = mod
        self.qual = qual
        self.fn = fn
        self.mode = mode   # "jit" | "loop" | "static" (call sites only)
        self.loop_mode = mode == "loop"
        self.sync_rule = "hot-loop-sync" if self.loop_mode else \
            "jit-host-sync"
        # Names that hold TRACE-TIME Python values, not tracers: the
        # function's own static_argnames plus anything unpacked from a
        # `.shape`/`.ndim`/len() — casting those is how shape math is
        # DONE under jit, not a sync hazard.
        self.static_names: set = set(
            mod.jit_info.get(qual, {}).get("statics", ()))
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                src = node.value
                is_static_src = (
                    (isinstance(src, ast.Attribute)
                     and src.attr in ("shape", "ndim", "size"))
                    or (isinstance(src, ast.Subscript)
                        and isinstance(src.value, ast.Attribute)
                        and src.value.attr == "shape")
                    or (isinstance(src, ast.Call)
                        and isinstance(src.func, ast.Name)
                        and src.func.id == "len"))
                if is_static_src:
                    for tgt in node.targets:
                        for el in ast.walk(tgt):
                            if isinstance(el, ast.Name):
                                self.static_names.add(el.id)

    def visit_FunctionDef(self, node):
        if node is self.fn:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    def visit_If(self, node: ast.If):
        if _is_tracer_guard(node):
            return   # eager-only / trace-only split: both sides exempt
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = _dotted(node.func)
        leaf = name.rsplit(".", 1)[-1]
        head = name.split(".", 1)[0] if name else ""
        line = node.lineno
        if self.mode != "static" and isinstance(node.func, ast.Attribute):
            if node.func.attr in SYNC_ATTR_CALLS and not node.args:
                self._flag(self.sync_rule, line,
                           f"`.{node.func.attr}()` forces a host-device "
                           f"sync")
            if head in self.mod.np_aliases and leaf in ("asarray",
                                                        "array"):
                self._flag(self.sync_rule, line,
                           f"`{name}(...)` copies device values to host")
            if name in ("jax.device_get", "jax.block_until_ready"):
                self._flag(self.sync_rule, line,
                           f"`{name}(...)` blocks on the device")
            if not self.loop_mode:
                if head == "time" and leaf in IMPURE_TIME:
                    self._flag("jit-impure", line,
                               f"`{name}()` under jit reads the "
                               f"trace-time clock")
                if head == "random":
                    self._flag("jit-impure", line,
                               f"`{name}()` under jit bakes one sample "
                               f"into the trace")
        elif self.mode == "jit" and leaf in SCALAR_CASTS and node.args:
            arg = node.args[0]
            benign = (
                isinstance(arg, ast.Constant)
                or (isinstance(arg, ast.Name)
                    and arg.id in self.static_names)
                or (isinstance(arg, ast.Attribute)
                    and arg.attr in ("shape", "ndim", "size")))
            if not benign:
                self._flag("jit-scalar-cast", line,
                           f"`{leaf}(...)` on a non-literal forces a "
                           f"concrete value out of the tracer")
        # static-by-keyword at resolvable call sites of jit functions.
        callee = self.ctx.resolve(self.mod, name)
        if callee is not None:
            cmod, cqual = callee
            info = cmod.jit_info.get(cqual)
            if info and info["statics"] and node.args:
                hit = [info["params"][i]
                       for i in range(min(len(node.args),
                                          len(info["params"])))
                       if info["params"][i] in info["statics"]]
                if hit:
                    self._flag("static-by-keyword", line,
                               f"call to jit fn {cqual} passes static "
                               f"arg(s) {', '.join(hit)} positionally "
                               f"(statics must go by keyword)")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if self.mode == "jit" and _dotted(node) == "os.environ":
            self._flag("jit-impure", node.lineno,
                       "`os.environ` under jit reads the trace-time "
                       "environment")
        self.generic_visit(node)

    def _flag(self, rule: str, line: int, why: str) -> None:
        if self.mod.src.allows(line, rule):
            return
        if allowed(self.ctx.allowlist, rule, self.mod.src.rel, self.qual):
            return
        where = {"loop": "hot loop", "jit": "jit-reachable code",
                 "static": "code"}[self.mode]
        self.ctx.findings.append(Finding(
            rule, self.mod.src.rel, line,
            f"{why} (in {where} {self.qual})"))


class _PassCtx:
    def __init__(self, modules: dict, allowlist: set):
        self.modules = modules         # dotted name -> ModuleInfo
        self.allowlist = allowlist
        self.findings: list = []

    def resolve(self, mod: ModuleInfo, dotted: str):
        """(ModuleInfo, qualname) for a call name, or None."""
        if not dotted:
            return None
        if "." not in dotted:
            if dotted in mod.functions:
                return (mod, dotted)
            imp = mod.from_imports.get(dotted)
            if imp:
                target = self._module(imp[0], mod)
                if target and imp[1] in target.functions:
                    return (target, imp[1])
            return None
        head, rest = dotted.split(".", 1)
        if head == "self":
            if "." in rest:
                return None
            for qual in mod.functions:
                if qual.endswith(f".{rest}") or qual == rest:
                    return (mod, qual)
            return None
        target_mod = mod.import_aliases.get(head)
        if target_mod is None and head in mod.from_imports:
            imod, iname = mod.from_imports[head]
            target_mod = f"{imod}.{iname}"
        if target_mod:
            target = self._module(target_mod, mod)
            if target and rest in target.functions:
                return (target, rest)
        return None

    def _module(self, dotted: str, frm: ModuleInfo):
        if dotted.startswith("."):
            base = frm.name.rsplit(".", 1)[0]
            dotted = base + dotted.rstrip(".")
        for name, m in self.modules.items():
            if name == dotted or name.endswith("." + dotted):
                return m
        return None


def _called_quals(ctx: _PassCtx, mod: ModuleInfo, fn: ast.FunctionDef):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            r = ctx.resolve(mod, _dotted(node.func))
            if r is not None:
                yield r
        elif isinstance(node, ast.Name):
            # bare function references (passed to scan/map/jit)
            r = ctx.resolve(mod, node.id)
            if r is not None:
                yield r


def run(files, allowlist: set | None = None) -> list:
    allowlist = allowlist or set()
    modules = {}
    for src in files:
        mod = ModuleInfo(src)
        modules[mod.name] = mod
    ctx = _PassCtx(modules, allowlist)

    # Transitive jit-reachability from decorated/wrapped roots.
    reachable: set = set()
    work = []
    for mod in modules.values():
        for qual in mod.jit_roots():
            work.append((mod, qual))
    while work:
        mod, qual = work.pop()
        key = (mod.name, qual)
        if key in reachable:
            continue
        reachable.add(key)
        fn = mod.functions.get(qual)
        if fn is None:
            continue
        for r in _called_quals(ctx, mod, fn):
            if (r[0].name, r[1]) not in reachable:
                work.append(r)

    visited: set = set()
    for mod_name, qual in sorted(reachable):
        mod = modules[mod_name]
        fn = mod.functions.get(qual)
        if fn is not None:
            visited.add((mod_name, qual))
            _HotChecker(ctx, mod, qual, fn, mode="jit").visit(fn)

    # Hot serving loops: body-only, syncs must be deliberate.
    for suffix, quals in HOT_LOOPS.items():
        mod = ctx._module(suffix, next(iter(modules.values())))
        if mod is None:
            continue
        for qual in quals:
            fn = mod.functions.get(qual)
            if fn is not None and (mod.name, qual) not in reachable:
                visited.add((mod.name, qual))
                _HotChecker(ctx, mod, qual, fn, mode="loop").visit(fn)

    # static-by-keyword applies at EVERY call site of a jit function,
    # hot or cold — a cold caller compiles just as wrong.
    for mod in modules.values():
        for qual, fn in mod.functions.items():
            if (mod.name, qual) not in visited and "<locals>" not in qual:
                _HotChecker(ctx, mod, qual, fn, mode="static").visit(fn)
    return ctx.findings
