"""Seeded registry-drift violations for the ``registry`` pass.  NOT
scanned by the default run (the env scanner skips tools/lint/fixtures);
tests/test_lint.py points the pass at this file explicitly."""

import os


def read_knobs():
    # VIOLATION env-undocumented (when scanned): no catalog entry.
    return os.environ.get("TPUBC_FIXTURE_UNDOCUMENTED", "0")


def emit_metrics(reg):
    # VIOLATION metric-counter-name: counter without _total.
    reg.inc("fixture_requests")
    # VIOLATION metric-counter-name: gauge masquerading as a counter.
    reg.set_gauge("fixture_blocks_total", 4)
    # VIOLATION metric-type-conflict: one name, two types.
    reg.observe("fixture_latency_ms", 1.0)
    reg.set_gauge("fixture_latency_ms", 2.0)
    # Clean: typed exactly once, suffix matches kind.
    reg.inc("fixture_retries_total")
    reg.observe("fixture_wait_ms", 3.0)
    # VIOLATION metric-label-drift: one family, two label-key sets.
    reg.inc("fixture_drift_total", labels={"zone": "a"})
    reg.inc("fixture_drift_total")
    # Clean: labeled the same way at every site.
    reg.observe("fixture_label_ok_ms", 1.0, labels={"arm": "x"})
    reg.observe("fixture_label_ok_ms", 2.0, labels={"arm": "y"})


# A miniature bench with an orphan hard key and an ambiguous family
# (tests feed this SOURCE to check_bench_keys via a temp file).
BENCH_FIXTURE_SRC = '''
_HIGHER_BETTER = ("per_sec", "speedup")
_LOWER_BETTER_SUFFIX = ("_ms",)
_LOWER_BETTER_ANYWHERE = ("bytes_per_token",)
_HARD_KEYS = ("fix_tokens_per_sec", "fix_never_emitted_per_sec",
              "fix_unjudged_widgets", "fix_speedup_ms")
_REGRESSION_EXEMPT = ("fix_noise_ms",)

def bench():
    out = {}
    out["fix_tokens_per_sec"] = 1.0        # clean: emitted + one family
    out["fix_unjudged_widgets"] = 2        # family-missing hard key
    out["fix_speedup_ms"] = 3.0            # BOTH families: ambiguous
    out["fix_noise_ms"] = 0.1              # exemption target: emitted
    return out
'''
