"""Seeded lock-discipline violations (tests/test_lint.py pins that the
``locks`` pass catches every one).  NOT scanned by the default run."""

import threading


class Account:
    def __init__(self):
        self._lock = threading.Lock()
        self.balance = 0  # guarded-by: _lock
        self.entries: list = []  # guarded-by: _lock
        self.owner = "nobody"   # unguarded on purpose: never flagged

    def deposit(self, n):
        with self._lock:
            self.balance += n
            self.entries.append(n)

    def peek(self):
        # VIOLATION lock-guard: read outside the lock.
        return self.balance

    def audit(self):
        with self._lock:
            total = self.balance
        # VIOLATION lock-guard: the with block ended.
        return total + len(self.entries)

    def _apply_locked(self, n):
        # Caller-holds convention: body reads are legal here.
        self.balance += n

    def safe_apply(self, n):
        with self._lock:
            self._apply_locked(n)

    def sloppy_apply(self, n):
        # VIOLATION lock-helper-unheld: _locked helper without the lock.
        self._apply_locked(n)

    def tolerated(self):
        return self.balance  # lint: allow(lock-guard) — demo escape
