"""Seeded endpoint-contract violations — tests/test_lint.py runs the
contracts pass over THIS file with an injected catalog and asserts each
drift class fires:

* ``/itemz``   — the producer renamed ``total`` to ``renamed_total``
  without updating the catalog: ``endpoint-key-stale`` (the documented
  ``total``) + ``endpoint-key-undocumented`` (the new name).
* ``/ghostz``  — served by the handler but absent from the catalog:
  ``endpoint-undocumented``.
* ``read_itemz`` — reads ``count`` which no producer emits:
  ``endpoint-ghost-read``; the ``items`` read is fine.
* ``read_retired`` — registered consumer whose variable reads nothing:
  ``endpoint-consumer-stale``.

NOT scanned by the default ``python -m tools.lint`` run (fixtures are
excluded from python_targets); nothing here executes.
"""


class FixtureServer:
    def __init__(self):
        outer = self

        class Handler:
            def do_GET(self):
                path = self.path
                if path == "/itemz":
                    payload = {
                        "items": list(outer.items),
                        "renamed_total": len(outer.items),
                    }
                    return payload
                if path == "/ghostz":
                    return {"boo": True}
                return {"error": "not found"}

        self.handler = Handler
        self.items = []


def read_itemz(doc):
    """Fixture consumer of /itemz."""
    n = doc.get("count") or 0          # ghost: producer renamed it away
    return n + len(doc["items"])       # fine: still produced


def read_retired(doc):
    """Registered against var ``payload`` which it never touches."""
    return doc
