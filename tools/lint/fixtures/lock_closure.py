"""Seeded handler-closure lock violations: a nested Handler class that
captures ``outer = self`` and touches guarded outer state from request
threads.  NOT scanned by the default run; tests/test_lint.py pins that
the closure re-run of the ``locks`` pass catches the bare read."""

import threading


class Exporter:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows: list = []  # guarded-by: _lock
        outer = self

        class Handler:
            def do_GET(self):
                # VIOLATION lock-guard: request-thread read of guarded
                # outer state without holding outer._lock.
                return list(outer.rows)

            def do_POST(self):
                # Clean: append under the outer lock.
                with outer._lock:
                    outer.rows.append(1)

            def do_DELETE(self):
                return len(outer.rows)  # lint: allow(lock-guard) — demo

        self.handler = Handler

    def push(self, row):
        with self._lock:
            self.rows.append(row)
