"""Seeded JAX hot-path violations for the ``hotpath`` pass.  NOT
scanned by the default run (and never imported — jax here is fictional
as far as the linter is concerned; the pass reads ASTs, not modules)."""

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("gain",))
def scale_rows(x, gain):
    # VIOLATION jit-host-sync: .item() forces a device round-trip.
    first = x[0, 0].item()
    # VIOLATION jit-host-sync: np.asarray pulls the tracer to host.
    host = np.asarray(x)
    # VIOLATION jit-impure: trace-time clock baked into the program.
    t = time.time()
    # VIOLATION jit-impure: trace-time environment read.
    flag = os.environ.get("HOTPATH_FIXTURE_FLAG", "")
    # VIOLATION jit-scalar-cast: float() on a traced value.
    bias = float(x[0, 1])
    return x * gain + first + host.sum() + t + len(flag) + bias


def helper(x):
    # Reachable FROM scale_all below -> jit-reachable rules apply.
    # VIOLATION jit-host-sync (transitive reachability).
    return x.item()


@jax.jit
def scale_all(x):
    if isinstance(x, jax.core.Tracer):
        # Tracer-guarded: NOT flagged (the eager/trace split idiom).
        probe = 0
    else:
        probe = int(np.asarray(x).sum())
    return helper(x) + probe


def cold_caller(x):
    # VIOLATION static-by-keyword: `gain` is static but passed
    # positionally (cold call sites compile just as wrong).
    return scale_rows(x, 3)


def fine_caller(x):
    return scale_rows(x, gain=3)   # clean: statics by keyword
