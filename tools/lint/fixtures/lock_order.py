"""Seeded lock-ordering / reacquire violations for the ``locks`` pass.
NOT scanned by the default run."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows: list = []  # guarded-by: _lock
        self.journal = Journal()

    def post(self):
        # Acquisition order here: Ledger._lock -> Journal._lock ...
        with self._lock:
            self.rows.append(1)
            self.journal.stamp()

    def total(self):
        with self._lock:
            return len(self.rows)


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self.marks = 0  # guarded-by: _lock
        self.ledger: "Ledger" = Ledger()

    def stamp(self):
        with self._lock:
            self.marks += 1

    def reconcile(self):
        # ... and here: Journal._lock -> Ledger._lock.
        # VIOLATION lock-order: the two paths disagree (deadlock cycle).
        with self._lock:
            return self.ledger.total()


class Nest:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.value += 1

    def double_bump(self):
        # VIOLATION lock-reacquire: bump() re-enters the non-reentrant
        # lock this frame already holds (self-deadlock, not a race).
        with self._lock:
            self.bump()
