"""Registry-drift pass: env vars, bench --check keys, metric names.

Four registries whose silent divergence has already cost this repo
debugging rounds (the stale int8 roofline, the duplicated gauge names):

* ENV VARS — every ``TPUBC_*`` identifier read anywhere (Python and C++
  via regex, plus charts/hack/CI) must appear in the curated catalog
  (tools/lint/env_catalog.py) and docs/ENV_VARS.md must be byte-equal to
  its rendering; every catalog entry must still exist in code; every
  ``TPUBC_*`` mention in the prose docs must name a real knob.
* BENCH KEYS — every hard ``--check`` key (and regression-exemption) in
  bench.py must be emitted by some bench section, and every emitted key
  must match at most ONE direction family (higher-better vs
  lower-better); a hard key matching neither family is ungated in the
  wrong direction.
* METRICS — every metric name recorded through the telemetry registry
  (Python ``inc``/``observe``/``set_gauge`` call sites plus the native
  ``Metrics::instance()`` ones) must keep ONE type (counter vs histogram
  vs gauge), and the ``_total`` suffix must match countership exactly —
  the Prometheus exposition renders types from that suffix, so a gauge
  named ``*_total`` lies to every scraper.
* METRIC LABELS — every metric family must use one consistent
  label-key set across all its Python and native call sites (Python
  ``labels={...}`` kwargs; native ``family{key="..."}`` name literals).
  A family observed both as ``serve_ttft_ms{priority=...}`` and as a
  bare ``serve_ttft_ms`` splits one series into two that no dashboard
  joins back; the deliberate blended+per-class pairs are allowlisted
  (``metric-label-drift <file>::<family>``).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from . import Finding, SourceFile, allowed
from .env_catalog import CATALOG, render

ENV_RE = re.compile(r"TPUBC_[A-Z0-9_]+")
ENV_DOC_PATH = "docs/ENV_VARS.md"

# Files/dirs scanned for env-var READS (code + deploy surface).
ENV_CODE_GLOBS = (
    "tpu_bootstrap/**/*.py", "bench.py", "tools/sim/**/*.py",
    "native/src/*.cc", "native/include/**/*.h", "native/bin/*.cc",
    "native/CMakeLists.txt",
    "charts/**/*.yaml", "charts/**/*.tpl",
    "hack/*.sh", ".github/workflows/*.yml",
    "tools/lint/fixtures/*.py",
)
# Prose docs checked for stale knob mentions.
ENV_DOC_GLOBS = ("ARCHITECTURE.md", "README.md", "MIGRATION.md")

# Native emission sites: anchored to the Metrics::instance() receiver so
# the Json builder's ``out.set("key", ...)`` never reads as a gauge, and
# multiline (the controller's .observe() calls wrap).  The name literal
# may carry a concat-label prefix: ``"family{key=\"" + value + "\"}"``.
NATIVE_METRIC_RE = re.compile(
    r"Metrics::instance\(\)\s*\.\s*(inc|observe|set|set_gauge)"
    r"\s*\(\s*\"((?:[^\"\\]|\\.)*)\"")
NATIVE_METRIC_GLOBS = ("native/src/*.cc", "native/bin/*.cc")

_KIND = {"inc": "counter", "observe": "histogram", "set_gauge": "gauge",
         "set": "gauge"}


# ---------------------------------------------------------------------------
# env vars
# ---------------------------------------------------------------------------

def scan_env_vars(root: Path, globs=ENV_CODE_GLOBS) -> dict:
    """name -> first (relpath, line) the identifier appears at."""
    seen: dict = {}
    for pattern in globs:
        for path in sorted(root.glob(pattern)):
            if "__pycache__" in path.parts or not path.is_file():
                continue
            if "fixtures" in path.parts and "tools" in path.parts:
                continue  # seeded violations don't demand documentation
            try:
                text = path.read_text()
            except (OSError, UnicodeDecodeError):
                continue
            for i, line in enumerate(text.splitlines(), 1):
                for m in ENV_RE.finditer(line):
                    seen.setdefault(
                        m.group(0), (str(path.relative_to(root)), i))
    return seen


def check_env_vars(root: Path, catalog=None) -> list:
    catalog = CATALOG if catalog is None else catalog
    findings = []
    seen = scan_env_vars(root)
    for name, (rel, line) in sorted(seen.items()):
        if name not in catalog:
            findings.append(Finding(
                "env-undocumented", rel, line,
                f"{name} is read by the code but missing from "
                f"tools/lint/env_catalog.py (+ docs/ENV_VARS.md)"))
    for name in sorted(set(catalog) - set(seen)):
        findings.append(Finding(
            "env-stale-doc", "tools/lint/env_catalog.py", 1,
            f"{name} is documented but nothing in the tree reads it"))
    doc = root / ENV_DOC_PATH
    if catalog is CATALOG:
        want = render()
        have = doc.read_text() if doc.exists() else ""
        if have != want:
            findings.append(Finding(
                "env-docs-drift", ENV_DOC_PATH, 1,
                "docs/ENV_VARS.md is out of date — regenerate with "
                "`python -m tools.lint --write-env-docs`"))
    for pattern in ENV_DOC_GLOBS:
        for path in sorted(root.glob(pattern)):
            for i, line in enumerate(path.read_text().splitlines(), 1):
                for m in ENV_RE.finditer(line):
                    if m.group(0) not in catalog and m.group(0) in seen:
                        continue  # caught above as env-undocumented
                    if m.group(0) not in catalog:
                        findings.append(Finding(
                            "env-stale-doc",
                            str(path.relative_to(root)), i,
                            f"{m.group(0)} is mentioned here but no "
                            f"code reads it"))
    return findings


# ---------------------------------------------------------------------------
# bench --check keys
# ---------------------------------------------------------------------------

def _tuple_of_strings(node: ast.AST) -> list:
    out = []
    for el in ast.walk(node):
        if isinstance(el, ast.Constant) and isinstance(el.value, str):
            out.append(el.value)
    return out


def _emitted_patterns(tree: ast.AST) -> list:
    """(pattern-regex, line) for every key the bench can emit: literal
    and f-string keys of subscript stores plus dict literals (section
    result blocks, .update() payloads)."""
    pats = []

    def add(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            pats.append((re.escape(node.value), node.lineno, node.value))
        elif isinstance(node, ast.JoinedStr):
            rx = ""
            for part in node.values:
                if isinstance(part, ast.Constant):
                    rx += re.escape(str(part.value))
                else:
                    rx += r"[A-Za-z0-9_.\-]+"
            pats.append((rx, node.lineno, None))

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, ast.Subscript):
                    add(tgt.slice)
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if key is not None:
                    add(key)
    return pats


def _embedded_scripts(tree: ast.AST):
    """The bench runs its workload half from embedded ``*_SCRIPT``
    source strings (subprocess isolation); their emitted keys count."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_SCRIPT")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            try:
                yield ast.parse(node.value.value)
            except SyntaxError:
                yield None   # surfaced by the caller as bench-structure


def check_bench_keys(bench_path: Path, rel: str = "bench.py") -> list:
    findings: list = []
    tree = ast.parse(bench_path.read_text(), filename=str(bench_path))
    consts: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name in ("_HARD_KEYS", "_HIGHER_BETTER",
                        "_LOWER_BETTER_SUFFIX", "_LOWER_BETTER_ANYWHERE",
                        "_REGRESSION_EXEMPT"):
                consts[name] = (_tuple_of_strings(node.value),
                                node.lineno)
    for want in ("_HARD_KEYS", "_HIGHER_BETTER", "_LOWER_BETTER_SUFFIX",
                 "_LOWER_BETTER_ANYWHERE"):
        if want not in consts:
            findings.append(Finding(
                "bench-structure", rel, 1,
                f"could not locate {want} in the bench — the drift "
                f"pass is blind without it"))
            return findings
    higher, _ = consts["_HIGHER_BETTER"]
    lower_sfx, _ = consts["_LOWER_BETTER_SUFFIX"]
    lower_any, _ = consts["_LOWER_BETTER_ANYWHERE"]

    def direction(key: str) -> set:
        d = set()
        if any(s in key for s in higher):
            d.add("higher")
        if (any(key.endswith(s) for s in lower_sfx)
                or any(s in key for s in lower_any)):
            d.add("lower")
        return d

    emitted = _emitted_patterns(tree)
    for sub in _embedded_scripts(tree):
        if sub is None:
            findings.append(Finding(
                "bench-structure", rel, 1,
                "an embedded *_SCRIPT source string does not parse — "
                "its emitted keys are invisible to the drift gate"))
            continue
        emitted += _emitted_patterns(sub)

    def is_emitted(key: str) -> bool:
        return any(re.fullmatch(rx, key) for rx, _, _ in emitted)

    hard, hard_line = consts["_HARD_KEYS"]
    for key in hard:
        if not is_emitted(key):
            findings.append(Finding(
                "bench-orphan-check-key", rel, hard_line,
                f"--check hard key {key!r} is not emitted by any bench "
                f"section"))
        d = direction(key)
        if len(d) == 0:
            findings.append(Finding(
                "bench-family-missing", rel, hard_line,
                f"--check hard key {key!r} matches no higher/lower-"
                f"better family — its regressions are invisible"))
        elif len(d) == 2:
            findings.append(Finding(
                "bench-family-ambiguous", rel, hard_line,
                f"--check hard key {key!r} matches BOTH direction "
                f"families — the gate's direction is undefined"))
    for key in consts.get("_REGRESSION_EXEMPT", ([], 0))[0]:
        if not is_emitted(key):
            findings.append(Finding(
                "bench-orphan-check-key", rel,
                consts["_REGRESSION_EXEMPT"][1],
                f"regression exemption {key!r} matches no emitted key"))
    # Any emitted literal key claimed by BOTH families is misjudged.
    flagged = set()
    for _, line, literal in emitted:
        if literal is None or literal in flagged:
            continue  # f-string keys are judged per concrete name
        if len(direction(literal)) == 2:
            flagged.add(literal)
            findings.append(Finding(
                "bench-family-ambiguous", rel, line,
                f"bench key {literal!r} matches BOTH direction "
                f"families"))
    return findings


# ---------------------------------------------------------------------------
# metric names
# ---------------------------------------------------------------------------

def _call_labels(node: ast.Call):
    """frozenset of label keys for a registry call: the ``labels={...}``
    kwarg's literal keys, empty when absent, None when the kwarg exists
    but is not a string-keyed dict literal (dynamic — not judged)."""
    for kw in node.keywords:
        if kw.arg != "labels":
            continue
        if isinstance(kw.value, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in kw.value.keys):
            return frozenset(k.value for k in kw.value.keys)
        return None
    return frozenset()


def _python_metric_sites(files) -> list:
    """(pattern, is_pattern, kind, rel, line, labels) per call site."""
    sites = []
    for src in files:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("inc", "observe", "set_gauge")
                    and node.args):
                continue
            arg = node.args[0]
            kind = _KIND[node.func.attr]
            labels = _call_labels(node)
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                sites.append((arg.value, False, kind, src.rel,
                              node.lineno, labels))
            elif isinstance(arg, ast.JoinedStr):
                rx = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant):
                        rx += re.escape(str(part.value))
                    else:
                        rx += r"[A-Za-z0-9_]+"
                sites.append((rx, True, kind, src.rel, node.lineno,
                              labels))
    return sites


def _native_metric_sites(root: Path) -> list:
    """(name, is_pattern, kind, rel, line, labels) per native call site;
    label keys are parsed out of concat-labeled name literals like
    ``"tpubc_scrape_backoff_seconds{replica=\\""``."""
    sites = []
    for pattern in NATIVE_METRIC_GLOBS:
        for path in sorted(root.glob(pattern)):
            text = path.read_text()
            rel = str(path.relative_to(root))
            for m in NATIVE_METRIC_RE.finditer(text):
                literal = m.group(2)
                family, _, label_part = literal.partition("{")
                if not re.fullmatch(r"[a-z0-9_]+", family):
                    continue
                labels = frozenset(
                    re.findall(r"([A-Za-z0-9_]+)=", label_part))
                line = text.count("\n", 0, m.start()) + 1
                sites.append((family, False, _KIND[m.group(1)], rel,
                              line, labels))
    return sites


def check_metrics(sites, allowlist: set | None = None) -> list:
    allowlist = allowlist or set()
    findings: list = []
    concrete: dict = {}   # name -> (kind, rel, line)
    patterns = []
    for name, is_pat, kind, rel, line, _labels in sites:
        if is_pat:
            patterns.append((name, kind, rel, line))
            continue
        prior = concrete.get(name)
        if prior and prior[0] != kind:
            findings.append(Finding(
                "metric-type-conflict", rel, line,
                f"metric {name!r} recorded as {kind} here but as "
                f"{prior[0]} at {prior[1]}:{prior[2]} — one name, one "
                f"type"))
        concrete.setdefault(name, (kind, rel, line))
    for name, (kind, rel, line) in sorted(concrete.items()):
        if allowed(allowlist, "metric-counter-name", rel, name):
            continue
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                "metric-counter-name", rel, line,
                f"counter {name!r} must end in _total (the Prometheus "
                f"exposition types series by that suffix)"))
        elif kind != "counter" and name.endswith("_total"):
            findings.append(Finding(
                "metric-counter-name", rel, line,
                f"{kind} {name!r} ends in _total and will render as a "
                f"counter to every scraper — rename it"))
    for rx, kind, rel, line in patterns:
        for name, (ckind, crel, cline) in concrete.items():
            if ckind != kind and re.fullmatch(rx, name):
                findings.append(Finding(
                    "metric-type-conflict", rel, line,
                    f"metric pattern {rx!r} ({kind}) collides with "
                    f"{name!r} ({ckind}) at {crel}:{cline}"))
    return findings


def check_metric_labels(sites, allowlist: set | None = None) -> list:
    """One family, one label schema: every concrete call site of a
    metric family must use the same label-key set.  The deliberate
    blended-aggregate + per-class pairs carry an allowlist entry
    (``metric-label-drift <file>::<family>``) so NEW drift still
    fails."""
    allowlist = allowlist or set()
    findings: list = []
    fams: dict = {}   # family -> {frozenset(label keys): (rel, line)}
    for name, is_pat, kind, rel, line, labels in sites:
        if is_pat or labels is None:
            continue   # dynamic names/labels are not judged
        fams.setdefault(name, {}).setdefault(labels, (rel, line))
    for name in sorted(fams):
        variants = fams[name]
        if len(variants) <= 1:
            continue
        if any(allowed(allowlist, "metric-label-drift", rel, name)
               for rel, _ in variants.values()):
            continue

        def fmt(keys):
            return "{" + ",".join(sorted(keys)) + "}" if keys \
                else "(unlabeled)"

        where = "; ".join(
            f"{fmt(keys)} at {rel}:{line}"
            for keys, (rel, line) in sorted(
                variants.items(), key=lambda kv: sorted(kv[0])))
        rel, line = min(variants.values())
        findings.append(Finding(
            "metric-label-drift", rel, line,
            f"metric family {name!r} is recorded with {len(variants)} "
            f"different label-key sets: {where} — one family, one "
            f"label schema (allowlist the deliberate blend)"))
    return findings


# ---------------------------------------------------------------------------

def run(root: Path, allowlist: set | None = None, files=None) -> list:
    from . import python_targets
    files = python_targets(root) if files is None else files
    findings = check_env_vars(root)
    bench = root / "bench.py"
    if bench.exists():
        findings += check_bench_keys(bench)
    sites = _python_metric_sites(files) + _native_metric_sites(root)
    findings += check_metrics(sites, allowlist)
    findings += check_metric_labels(sites, allowlist)
    return findings
