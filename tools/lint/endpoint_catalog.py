"""The curated endpoint/JSON contract catalog — tools.lint.contracts
gates the tree against THIS file, and docs/ENDPOINTS.md is generated
from it (``python -m tools.lint --write-endpoint-docs``).

One ``Endpoint`` per (server, path): which handler serves it, which
functions assemble its payload (producers), who reads it across process
boundaries (consumers), and — for static-JSON endpoints — the exact
flat key universe the payload may carry.  Changing a snapshot key
WITHOUT updating this catalog (and the docs) fails CI from both sides:
the producer diff fires ``endpoint-key-undocumented`` /
``endpoint-key-stale`` and any stranded reader fires
``endpoint-ghost-read``.

Kinds:

* ``json``    — static JSON shape; ``keys`` is the exact flat universe
  (nested payload dict keys included, list element dicts too).
* ``metrics`` — dynamic metric-name keyed JSON (``/metrics.json``);
  consumer reads are gated against the real emission sites instead,
  with the histogram-suffix and ``family{label="v"}`` grammar applied.
* ``prom``    — Prometheus/plain text; no JSON key contract.
"""

from __future__ import annotations

from collections import namedtuple

# file: repo-relative source ('.cc' files use the native extractor).
# func: Python qualname (``Cls.meth.<locals>.Handler.do_GET``) or the
#       C++ qualified name.  var: restrict extraction to dicts flowing
#       through that local (None = the whole function).  route: scope a
#       multiplexed handler's keys to one dispatch branch.
Producer = namedtuple("Producer", "file func var route",
                      defaults=(None, None))
Consumer = namedtuple("Consumer", "file func var")
Endpoint = namedtuple(
    "Endpoint", "server path aliases kind producers consumers keys desc",
    defaults=((), "json", (), (), (), ""))

# Every HTTP server in the tree and the handler(s) whose dispatch tests
# define its route set (func=None for native files: routes are scanned
# whole-file).
SERVERS = {
    "ingress": (
        ("tpu_bootstrap/workload/ingress.py",
         "IngressServer.__init__.<locals>.Handler.do_GET"),
        ("tpu_bootstrap/workload/ingress.py",
         "IngressServer.__init__.<locals>.Handler.do_POST"),
    ),
    "worker": (
        ("tpu_bootstrap/telemetry.py",
         "start_metrics_server.<locals>.Handler.do_GET"),
    ),
    "fleetz": (
        ("tpu_bootstrap/workload/fleetz.py",
         "FleetAggregator.__init__.<locals>.Handler.do_GET"),
    ),
    "router": (
        ("tpu_bootstrap/workload/router.py",
         "FleetRouter.__init__.<locals>.Handler.do_GET"),
        ("tpu_bootstrap/workload/router.py",
         "FleetRouter.__init__.<locals>.Handler.do_POST"),
    ),
    "controller": (("native/bin/controller.cc", None),),
    "synchronizer": (("native/bin/synchronizer.cc", None),),
}

_ING = "tpu_bootstrap/workload/ingress.py"
_SRV = "tpu_bootstrap/workload/serving.py"
_TEL = "tpu_bootstrap/telemetry.py"
_FLZ = "tpu_bootstrap/workload/fleetz.py"
_RTR = "tpu_bootstrap/workload/router.py"
_ING_GET = "IngressServer.__init__.<locals>.Handler.do_GET"
_ING_POST = "IngressServer.__init__.<locals>.Handler.do_POST"
_TEL_GET = "start_metrics_server.<locals>.Handler.do_GET"
_FLZ_GET = "FleetAggregator.__init__.<locals>.Handler.do_GET"
_RTR_GET = "FleetRouter.__init__.<locals>.Handler.do_GET"
_RTR_POST = "FleetRouter.__init__.<locals>.Handler.do_POST"

# Both in-process tracers (Python telemetry.Tracer, native trace.cc)
# publish the same span document shape — the stitcher depends on it.
_TRACE_KEYS = ("attrs", "dropped", "dur_us", "name", "parent_id",
               "process", "span_id", "spans", "start_us", "trace_id")
_PY_TRACE_PRODUCERS = (Producer(_TEL, "Tracer.to_json"),
                       Producer(_TEL, "Span.to_dict"))
_STITCH_CONSUMERS = (Consumer(_FLZ, "stitch", "doc"),)

_ENTRIES = (
    # ---- ingress (per-replica serving front end) ------------------------
    Endpoint(
        "ingress", "/v1/generate", (), "json",
        producers=(Producer(_ING, _ING_POST, route="/v1/generate"),
                   Producer(
                       _ING,
                       "IngressServer.__init__.<locals>.Handler._pump"),),
        consumers=(Consumer("bench.py", "slo_report", "out"),
                   Consumer(_RTR, "FleetRouter._on_event", "ev"),),
        keys=("Retry-After", "cached_tokens", "deadline_exceeded", "done",
              "draining", "error", "queue_position", "queued",
              "request_id", "timing", "tokens", "trace_id"),
        desc="Blocking generation API. `Retry-After` is the 429 "
             "admission-backpressure response's header literal; the "
             "rest is the completion/queue-position body. A client "
             "`request_id` idempotency key is echoed everywhere, and a "
             "re-submitted id attaches to the existing stream/result "
             "instead of executing twice."),
    Endpoint(
        "ingress", "/healthz", ("/health",), "json",
        producers=(Producer(_ING, _ING_GET, route="/healthz"),),
        consumers=(Consumer(_FLZ, "FleetAggregator._fold", "hz"),
                   Consumer(_RTR, "FleetRouter._fold_scrape", "hz"),),
        keys=("active", "beat_age_ms", "draining", "last_error", "ok",
              "p50_total_ms", "p50_ttft_ms", "queued", "served",
              "stalled_ms"),
        desc="Replica liveness + drain state; the fleet poller's "
             "required scrape (`ok` feeds the healthy count). "
             "`beat_age_ms` is the always-on engine heartbeat age the "
             "router's hedge trigger watches."),
    Endpoint(
        "ingress", "/metrics", (), "prom",
        desc="Prometheus text exposition of the serving registry."),
    Endpoint(
        "ingress", "/metrics.json", (), "metrics",
        consumers=(Consumer(_FLZ, "FleetAggregator.fleetz_json", "m"),
                   Consumer("bench.py", "slo_report", "serve_json")),
        desc="Instant JSON snapshot of the serving metric registry "
             "(`?window=N` serves the time-series ring). Series names "
             "carry `{label=\"v\"}` and histogram suffixes."),
    Endpoint(
        "ingress", "/requestz", (), "json",
        producers=(Producer(_ING, _ING_GET, route="/requestz"),
                   Producer(_SRV, "RequestLog.snapshot"),
                   Producer(_SRV, "RequestLog.arrivals"),
                   Producer(_SRV, "RequestLog._phases_locked", var="out")),
        consumers=(Consumer("bench.py", "slo_report", "requestz"),
                   Consumer("tools/sim/harness.py", "load_trace", "rec")),
        keys=("cached_tokens", "capacity", "deadline", "device_ms",
              "device_ms_by_kind", "dropped_events", "enabled", "error",
              "events", "footprint_blocks", "generated", "legs",
              "max_new", "phases", "preemptions", "priority",
              "prompt_len", "reason", "requests", "rid", "state",
              "submit_us", "t_arrival_us", "total_ms", "trace_id"),
        desc="Per-request lifecycle log: states, preemption legs, "
             "phase timings, device-time attribution. "
             "`?format=jsonl` flips to the flat arrival-record export "
             "(rid, t_arrival_us, prompt_len, max_new, priority, "
             "deadline, trace_id — one line per request, arrival "
             "order), the capture half of the tools.sim "
             "capture/replay loop."),
    Endpoint(
        "ingress", "/poolz", (), "json",
        producers=(Producer(_ING, _ING_GET, route="/poolz"),
                   Producer(_ING, "IngressServer._publish_poolz"),
                   Producer(_SRV, "_PoolBase.snapshot"),
                   Producer(_SRV, "_PoolBase._slot_json"),
                   Producer(_SRV, "PagedPool.snapshot"),
                   Producer(_SRV, "PagedPool._slot_json"),
                   Producer(_SRV, "HostBlockPool.snapshot_json"),
                   Producer(_SRV, "Scheduler.snapshot")),
        consumers=(Consumer("bench.py", "slo_report", "poolz"),
                   Consumer(_FLZ, "FleetAggregator.fleetz_json", "pool"),
                   Consumer(_RTR, "FleetRouter._fold_scrape", "pz")),
        keys=("active", "as_of_us", "available", "batch_size",
              "block_size", "blocks", "bytes", "cache_digest", "cached",
              "cached_tokens", "capacity", "compactness", "deadline",
              "dropped", "engine", "evictions", "expected_new_ema",
              "free", "free_slots", "generated", "hash_hits",
              "history_tokens", "hit_tokens", "host",
              "imminent_growth_blocks", "ledger", "live", "overcommit",
              "paged_kernel", "peak_used", "pool", "prefilled",
              "prefilling", "prefix_cache", "priority", "prompt_len",
              "queue_depth", "queue_wait_p50_ms", "registered_blocks",
              "remaining", "resume", "rid", "scheduler", "seq",
              "shared_blocks", "slot", "slots", "stats", "swap_ins",
              "swap_outs", "total", "waiting",
              "watermark_headroom_blocks"),
        desc="Engine pool + scheduler snapshot: slots, block-allocator "
             "gauges, prefix-cache stats, host-tier accounting, "
             "admission queue, the busy/idle ledger."),
    Endpoint(
        "ingress", "/cachez", (), "json",
        producers=(Producer(_ING, _ING_GET, route="/cachez"),
                   Producer(_SRV, "BlockAllocator.digest_json"),
                   Producer(_SRV, "PagedPool._cache_digest_json"),
                   Producer(_SRV, "HostBlockPool.digest_json")),
        consumers=(Consumer(_FLZ, "FleetAggregator.fleetz_json",
                            "digest"),),
        keys=("as_of_us", "block_size", "blocks", "bytes", "digest",
              "fps", "host", "version"),
        desc="Prefix-cache content digest (block fingerprints), "
             "HBM tier plus parked host tier, for cross-replica cache "
             "comparison."),
    Endpoint(
        "ingress", "/traces.json", (), "json",
        producers=_PY_TRACE_PRODUCERS,
        consumers=_STITCH_CONSUMERS,
        keys=_TRACE_KEYS,
        desc="The replica's span ring buffer; the fleetz stitcher joins "
             "these across replicas by trace id."),
    Endpoint(
        "ingress", "/profilez", (), "json",
        producers=(Producer(
                       _ING,
                       "IngressServer.__init__.<locals>.Handler._profilez"),
                   Producer(_ING, "IngressServer._profile_tick",
                            var="result")),
        keys=("artifact_dir", "busy_frac", "deadline", "dir", "error",
              "event", "ledger", "measured_ms", "mfu", "mode", "ms",
              "profiler_error", "requested_ms", "result"),
        desc="On-demand device-profile capture (POST): arms a "
             "bounded-duration capture on the engine thread and blocks "
             "for the result."),

    # ---- worker (bare telemetry server, no ingress) ---------------------
    Endpoint(
        "worker", "/metrics", (), "prom",
        desc="Prometheus text exposition of the worker registry."),
    Endpoint(
        "worker", "/metrics.json", (), "metrics",
        consumers=(Consumer("native/src/reconcile_core.cc",
                            "workload_summary", "metrics"),),
        desc="Instant JSON metric snapshot; the controller's workload "
             "scrape reads progress/throughput series off it."),
    Endpoint(
        "worker", "/healthz", ("/health",), "json",
        producers=(Producer(_TEL, _TEL_GET, route="/healthz"),),
        consumers=(Consumer(_FLZ, "FleetAggregator._fold", "hz"),),
        keys=("error", "heartbeat_age_ms", "last_step", "ok",
              "stalled_ms"),
        desc="Training-loop heartbeat health: stall detection drives "
             "`ok`."),
    Endpoint(
        "worker", "/statusz", (), "json",
        producers=(Producer(_TEL, _TEL_GET, route="/statusz"),),
        keys=("dropped", "error", "heartbeat_age_ms", "last_step",
              "metrics_series", "process", "spans", "tracer"),
        desc="Single-page worker debug snapshot (heartbeat + registry "
             "size + tracer occupancy)."),
    Endpoint(
        "worker", "/traces.json", (), "json",
        producers=_PY_TRACE_PRODUCERS,
        consumers=_STITCH_CONSUMERS,
        keys=_TRACE_KEYS,
        desc="The worker's span ring buffer (same shape as ingress)."),

    # ---- fleetz (fleet aggregator pane) ---------------------------------
    Endpoint(
        "fleetz", "/fleetz", (), "json",
        producers=(Producer(_FLZ, "FleetAggregator.fleetz_json"),
                   Producer(_FLZ, "SloEngine.evaluate"),
                   Producer(_FLZ, "SloEngine.alerts"),
                   Producer(_RTR, "breaker_view"),
                   Producer(_FLZ, _FLZ_GET, route="/fleetz")),
        consumers=(Consumer(_FLZ, _FLZ_GET, "snap"),
                   Consumer(_RTR, "FleetRouter._fetch_burn", "doc"),
                   Consumer(_RTR, "FleetRouter._discover_from_fleetz",
                            "doc"),),
        keys=("alerts", "as_of_us", "backoff_s", "blocks", "breaker",
              "burn", "burn_threshold", "busy_frac", "cache_digest",
              "cached", "digest_blocks", "error", "event", "failures",
              "firing", "fleet", "health", "healthy", "last_err",
              "last_ok_age_ms", "live", "mfu", "objectives", "poll_ms",
              "qps", "queue_depth", "replica", "replicas",
              "retry_in_s", "scrape_ms", "scrapes", "serve_qps",
              "serve_tokens_per_sec", "since_us", "slo", "state",
              "t_us", "tokens_per_sec", "total", "transitions",
              "window", "window_secs", "windows", "windows_s"),
        desc="The merged fleet pane: per-replica health/queue/cache "
             "columns plus a router-consistent `breaker` circuit view "
             "derived from scrape-backoff state, fleet rollups, SLO "
             "burn rates, firing alerts. `?replica=host:port` narrows "
             "the per-replica maps to one member (404 on unknown "
             "names); the fleet rollup stays fleet-wide. Per-objective "
             "fields under `objectives` come from "
             "`dataclasses.asdict(SloObjective)` and are not part of "
             "the static key contract."),
    Endpoint(
        "fleetz", "/metrics", (), "prom",
        desc="Federated Prometheus text: every replica's series "
             "re-labeled with `replica=\"host:port\"`."),
    Endpoint(
        "fleetz", "/metrics.json", (), "metrics",
        desc="The aggregator's own registry (scrape counters, poll "
             "latencies, fleet gauges)."),
    Endpoint(
        "fleetz", "/traces.json", (), "json",
        producers=(Producer(_FLZ, "stitch"),
                   Producer(_FLZ, "stitch_chrome"),
                   Producer(_FLZ, _FLZ_GET, route="/traces.json")),
        keys=("args", "attrs", "cat", "displayTimeUnit", "dropped",
              "dur", "error", "name", "parent_id", "ph", "pid",
              "process", "replicas", "span_id", "spans", "stitched",
              "tid", "traceEvents", "trace_id", "traces", "ts"),
        desc="Cross-replica stitched timeline (`?chrome=1` renders "
             "Chrome trace-event JSON instead)."),
    Endpoint(
        "fleetz", "/healthz", (), "json",
        producers=(Producer(_FLZ, _FLZ_GET, route="/healthz"),),
        keys=("error", "healthy", "ok", "replicas"),
        desc="The aggregator's own liveness + how many replicas it "
             "currently sees healthy."),

    # ---- router (fleet front door) --------------------------------------
    Endpoint(
        "router", "/v1/generate", (), "json",
        producers=(Producer(_RTR, "_ClientWriter._line"),
                   Producer(_RTR, "FleetRouter._route"),),
        keys=("Retry-After", "cached_tokens", "deadline_exceeded",
              "done", "draining", "error", "failover", "queue_position",
              "queued", "request_id", "timing", "tokens", "trace_id"),
        desc="The fleet front door: the full per-replica /v1/generate "
             "contract (stream and non-stream), placed on the longest "
             "fresh cache-digest match, least queue on ties. Every "
             "request carries a `request_id` idempotency key (minted "
             "if absent) and gets exactly one terminal outcome: "
             "pre-first-token deaths re-place on survivors silently, "
             "mid-stream deaths close with a terminal "
             "`\"failover\": true` error chunk, and an unroutable "
             "fleet answers 503 with the dynamic `Retry-After` header "
             "literal."),
    Endpoint(
        "router", "/routerz", (), "json",
        producers=(Producer(_RTR, _RTR_GET, route="/routerz"),
                   Producer(_RTR, "FleetRouter.routerz_json"),
                   Producer(_RTR, "CircuitBreaker.snapshot"),
                   Producer(_RTR, "AutoscaleController.snapshot"),),
        keys=("active", "as_of_us", "autoscale", "backoff_s",
              "beat_age_ms", "breaker", "cooldown_s", "digest_age_ms",
              "digest_blocks", "digest_stale_ms", "dispatches",
              "down_streak", "draining", "error", "failures",
              "hedge_ms", "inflight", "last", "last_err", "max", "min",
              "queue_depth", "replicas", "retries", "retry_in_s",
              "scrape_ms", "state", "up_streak"),
        desc="The router's placement table: per-replica breaker state, "
             "digest freshness, scraped queue/active, in-flight "
             "dispatch counts, drain flags, plus the autoscale "
             "controller's streaks and cooldown when one is armed."),
    Endpoint(
        "router", "/requestz", (), "json",
        producers=(Producer(_RTR, _RTR_GET, route="/requestz"),
                   Producer(_RTR, "FleetRouter.arrival_records"),
                   Producer(_RTR, "FleetRouter._note_arrival",
                            var="rec")),
        consumers=(Consumer("tools/sim/harness.py", "load_trace",
                            "rec"),),
        keys=("deadline", "error", "max_new", "priority", "prompt_len",
              "requests", "rid", "t_arrival_us", "trace_id"),
        desc="Fleet-level arrival capture: every accepted front-door "
             "request as a replayable arrival record (the router's "
             "idempotency key stands in for the engine rid). "
             "`?format=jsonl` streams one record per line — recorded "
             "production bursts become tools.sim scenarios via "
             "--replay-trace."),
    Endpoint(
        "router", "/healthz", (), "json",
        producers=(Producer(_RTR, _RTR_GET, route="/healthz"),),
        keys=("as_of_us", "error", "ok", "replicas", "routable"),
        desc="Router liveness: `ok` while at least one replica is "
             "routable (closed breaker, not draining); 503 otherwise."),
    Endpoint(
        "router", "/metrics", (), "prom",
        desc="Prometheus text exposition of the router registry "
             "(placement, failover, breaker, hedge, autoscale "
             "counters)."),
    Endpoint(
        "router", "/metrics.json", (), "metrics",
        desc="Instant JSON snapshot of the router registry "
             "(`?window=N` serves the time-series ring)."),

    # ---- controller (native) --------------------------------------------
    Endpoint(
        "controller", "/health", (), "prom",
        desc="Plain-text liveness."),
    Endpoint(
        "controller", "/metrics", (), "prom",
        desc="Prometheus text exposition of the native registry."),
    Endpoint(
        "controller", "/metrics.json", (), "metrics",
        consumers=(Consumer("bench.py", "slo_report", "m"),),
        desc="Instant JSON snapshot of the native metric registry "
             "(reconcile latencies, workqueue depth, scrape "
             "counters)."),
    Endpoint(
        "controller", "/statusz", (), "json",
        producers=(Producer("native/src/statusz.cc", "Statusz::to_json"),),
        consumers=(Consumer("bench.py", "slo_report", "statusz"),),
        keys=("evicted_objects", "generated_at_ms", "objects", "process",
              "ring_capacity", "state", "tracked_objects"),
        desc="Per-object reconcile state ring (`?object=` filters). "
             "Object names under `objects` are dynamic."),
    Endpoint(
        "controller", "/traces.json", (), "json",
        producers=(Producer("native/src/trace.cc", "Tracer::to_json"),),
        keys=_TRACE_KEYS,
        desc="The native tracer's span ring (same shape as the Python "
             "tracers — the stitcher depends on it)."),

    # ---- synchronizer (native) ------------------------------------------
    Endpoint(
        "synchronizer", "/health", (), "prom",
        desc="Plain-text liveness."),
    Endpoint(
        "synchronizer", "/metrics", (), "prom",
        desc="Prometheus text exposition of the native registry."),
    Endpoint(
        "synchronizer", "/metrics.json", (), "metrics",
        desc="Instant JSON snapshot of the native metric registry "
             "(pool capacity gauges, sync/conflict counters)."),
    Endpoint(
        "synchronizer", "/statusz", (), "json",
        producers=(Producer("native/src/statusz.cc", "Statusz::to_json"),),
        keys=("evicted_objects", "generated_at_ms", "objects", "process",
              "ring_capacity", "state", "tracked_objects"),
        desc="Per-object sync state ring."),
    Endpoint(
        "synchronizer", "/traces.json", (), "json",
        producers=(Producer("native/src/trace.cc", "Tracer::to_json"),),
        keys=_TRACE_KEYS,
        desc="The native tracer's span ring."),
)

CATALOG = {(e.server, e.path): e for e in _ENTRIES}

_HEADER = """\
# HTTP endpoint contracts

GENERATED FILE — do not edit by hand.  Source of truth:
`tools/lint/endpoint_catalog.py`; regenerate with
`python -m tools.lint --write-endpoint-docs`.  CI fails when this file
drifts from the catalog, when a handler serves an undocumented route,
when a producer's key set diverges from the documented one, or when a
consumer reads a key no producer emits (`python -m tools.lint --only
contracts`).
"""


def render() -> str:
    out = [_HEADER]
    for server in SERVERS:
        eps = sorted((e for e in _ENTRIES if e.server == server),
                     key=lambda e: e.path)
        if not eps:
            continue
        out.append(f"\n## `{server}`\n")
        for e in eps:
            alias = "".join(f", `{a}`" for a in e.aliases)
            out.append(f"\n### `{e.path}`{alias} ({e.kind})\n")
            if e.desc:
                out.append(f"\n{e.desc}\n")
            if e.kind == "json" and e.keys:
                keyline = ", ".join(f"`{k}`" for k in sorted(e.keys))
                out.append(f"\nKeys: {keyline}\n")
            elif e.kind == "metrics":
                out.append("\nKeys: dynamic — the metric registry's "
                           "series names (consumer reads are gated "
                           "against the emission sites).\n")
            if e.producers:
                out.append("\nProducers: "
                           + ", ".join(f"`{p.file}::{p.func}`"
                                       for p in e.producers) + "\n")
            if e.consumers:
                out.append("\nConsumers: "
                           + ", ".join(f"`{c.file}::{c.func}`"
                                       for c in e.consumers) + "\n")
    return "".join(out)
