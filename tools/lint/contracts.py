"""Endpoint-contract drift pass: producers, consumers, and the catalog.

PRs 9-12 made the replica's HTTP JSON shapes cross-process interfaces:
the fleetz aggregator polls ``/healthz``/``/metrics.json``/
``/traces.json``, bench ``--slo-report`` assembles from ``/requestz``/
``/poolz``/``/metrics.json``, and the native controller scrapes
``/metrics.json`` and serves its own ``/statusz``.  Nothing gated
producer/consumer drift on those shapes — a renamed ``snapshot()`` key
silently zeroed a fleet column.  This pass closes the loop against the
curated ``tools/lint/endpoint_catalog.py``:

* endpoint discovery — every route a server dispatches on must have a
  catalog entry (``endpoint-undocumented``) and every catalog entry a
  live route (``endpoint-stale``);
* producer keys — the flat key universe each endpoint's producer chain
  emits (AST: dict literals, ``var[k] =`` stores, ``.update({...})``;
  native: ``Json::object({{"k", ...}})`` / ``.set("k", ...)``) must
  match the catalog exactly (``endpoint-key-undocumented`` /
  ``endpoint-key-stale``);
* consumer reads — every key a registered consumer reads off an
  endpoint's payload (``var["k"]`` chains, ``var.get("k")``,
  ``"k" in var``; native ``var.get("k")``) must exist in the catalog
  (``endpoint-ghost-read``), and registered consumers must still read
  something (``endpoint-consumer-stale``);
* metrics endpoints — ``/metrics.json`` payload keys are dynamic, so
  consumer reads are gated against the REAL emission sites (Python
  registry calls + native ``Metrics::instance()`` sites) with the
  histogram suffix and ``name{label="v"}`` grammar applied;
* docs — ``docs/ENDPOINTS.md`` must be byte-identical to
  ``endpoint_catalog.render()`` (``--write-endpoint-docs``
  regenerates).

Route scoping: the three Python servers multiplex one ``do_GET`` over
many routes, so producer extraction attributes statements to routes via
the handler's own dispatch tests — positive ``path == "/x"`` /
``path in (...)`` / ``path.startswith("/x")`` branches scope their
bodies, and a negative ``if path not in (...): return`` narrows every
following statement.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from . import Finding, SourceFile, allowed
from . import endpoint_catalog as ec

ENDPOINT_DOC_PATH = Path("docs") / "ENDPOINTS.md"

# ---------------------------------------------------------------------------
# qualname resolution (classes nested in functions included)


def _functions(tree: ast.AST) -> dict:
    """{qualname: FunctionDef} with the runtime qualname convention —
    ``Outer.meth.<locals>.Handler.do_GET`` for handler classes defined
    inside server methods."""
    out: dict = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = prefix + child.name
                out[q] = child
                walk(child, q + ".<locals>.")
            elif isinstance(child, ast.ClassDef):
                walk(child, prefix + child.name + ".")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


# ---------------------------------------------------------------------------
# route dispatch recognition


def _path_expr(node: ast.AST) -> bool:
    """Is this expression the request path? ``path``/``route`` names or
    ``self.path``."""
    if isinstance(node, ast.Name) and node.id in ("path", "route"):
        return True
    return isinstance(node, ast.Attribute) and node.attr == "path"


def _str_elts(node: ast.AST) -> list | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return vals
    return None


def _route_test(test: ast.AST):
    """Classify a dispatch test -> ("pos"|"neg", [route literals]) or
    None. ``startswith`` counts as positive for its literal prefix."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        if not _path_expr(test.left):
            return None
        routes = _str_elts(test.comparators[0])
        if routes is None:
            return None
        op = test.ops[0]
        if isinstance(op, (ast.Eq, ast.In)):
            return ("pos", routes)
        if isinstance(op, (ast.NotEq, ast.NotIn)):
            return ("neg", routes)
        return None
    if (isinstance(test, ast.Call) and isinstance(test.func, ast.Attribute)
            and test.func.attr == "startswith"
            and _path_expr(test.func.value) and test.args):
        routes = _str_elts(test.args[0])
        if routes is not None:
            return ("pos", routes)
    return None


def served_routes(func: ast.FunctionDef) -> set:
    """Every route literal a handler function dispatches on."""
    routes: set = set()
    for node in ast.walk(func):
        if isinstance(node, ast.If):
            m = _route_test(node.test)
            if m:
                routes.update(m[1])
    return routes


# ---------------------------------------------------------------------------
# producer key extraction


def _dict_keys(node: ast.AST) -> set:
    """Every string key of every dict literal under ``node`` — the flat
    key universe (nested payload dicts contribute their keys too)."""
    keys: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
    return keys


def _stmt_keys(stmt: ast.stmt, var: str | None) -> set:
    """Producer keys introduced by one statement: dict literals (any,
    or only those flowing into ``var`` when given), ``v["k"] = ...``
    stores, and ``v.update({...})``."""
    keys: set = set()
    if var is None:
        keys |= _dict_keys(stmt)
    elif isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Name) and t.id == var:
                keys |= _dict_keys(stmt.value)
    for n in ast.walk(stmt):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Subscript)):
            sub = n.targets[0]
            if (isinstance(sub.value, ast.Name)
                    and (var is None or sub.value.id == var)
                    and isinstance(sub.slice, ast.Constant)
                    and isinstance(sub.slice.value, str)):
                keys.add(sub.slice.value)
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "update"
                and isinstance(n.func.value, ast.Name)
                and (var is None or n.func.value.id == var)):
            for a in n.args:
                keys |= _dict_keys(a)
    return keys


def _scoped_keys(stmts: list, scope, var: str | None, buckets: dict):
    """Attribute producer keys to routes. ``scope`` is None before any
    narrowing (keys land in the ``None`` bucket) or a tuple of route
    literals afterwards."""
    for stmt in stmts:
        if isinstance(stmt, ast.If):
            m = _route_test(stmt.test)
            if m and m[0] == "pos":
                _scoped_keys(stmt.body, tuple(m[1]), var, buckets)
                _scoped_keys(stmt.orelse, scope, var, buckets)
                continue
            if m and m[0] == "neg" and stmt.body and isinstance(
                    stmt.body[-1], (ast.Return, ast.Raise)):
                # ``if path not in (...): return`` — the body answers
                # OTHER routes (the 404); everything after is narrowed.
                _scoped_keys(stmt.body, ("*fallthrough*",), var, buckets)
                scope = tuple(m[1])
                continue
            _scoped_keys(stmt.body, scope, var, buckets)
            _scoped_keys(stmt.orelse, scope, var, buckets)
            continue
        if isinstance(stmt, (ast.Try, ast.With, ast.For, ast.While)):
            _scoped_keys(stmt.body, scope, var, buckets)
            for h in getattr(stmt, "handlers", ()):
                _scoped_keys(h.body, scope, var, buckets)
            _scoped_keys(getattr(stmt, "orelse", []), scope, var, buckets)
            _scoped_keys(getattr(stmt, "finalbody", []), scope, var,
                         buckets)
            continue
        for r in (scope if scope is not None else (None,)):
            buckets.setdefault(r, set()).update(_stmt_keys(stmt, var))


def producer_keys(func: ast.FunctionDef, var: str | None,
                  route: str | None) -> set:
    """The key universe one Producer spec contributes."""
    if route is None:
        keys: set = set()
        for stmt in func.body:
            keys |= _stmt_keys(stmt, var)
        return keys
    buckets: dict = {}
    _scoped_keys(func.body, None, var, buckets)
    keys = set(buckets.get(route, set()))
    # Shared prologue statements (before any narrowing) belong to every
    # route of the handler.
    keys |= buckets.get(None, set())
    return keys


# ---------------------------------------------------------------------------
# consumer read extraction


def _chain_keys(node: ast.AST, var: str):
    """Keys read through a subscript/.get chain rooted at ``var``:
    ``v["a"]["b"]`` and ``v.get("a", {}).get("b")`` yield a and b."""
    keys: list = []
    cur = node
    while True:
        if (isinstance(cur, ast.Subscript)
                and isinstance(cur.slice, ast.Constant)
                and isinstance(cur.slice.value, str)):
            keys.append((cur.slice.value, cur.lineno))
            cur = cur.value
            continue
        if (isinstance(cur, ast.Subscript)
                and isinstance(cur.slice, ast.Constant)
                and isinstance(cur.slice.value, int)):
            # list indexing inside a chain (requests[0]["rid"]) — step
            # through without contributing a key
            cur = cur.value
            continue
        if (isinstance(cur, ast.Call)
                and isinstance(cur.func, ast.Attribute)
                and cur.func.attr == "get" and cur.args
                and isinstance(cur.args[0], ast.Constant)
                and isinstance(cur.args[0].value, str)):
            keys.append((cur.args[0].value, cur.lineno))
            cur = cur.func.value
            continue
        break
    if isinstance(cur, ast.Name) and cur.id == var:
        return keys
    return []


def consumer_reads(func: ast.FunctionDef, var: str) -> list:
    """Every (key, line) the function reads off ``var``'s payload."""
    reads: list = []
    seen: set = set()
    for node in ast.walk(func):
        for key, line in _chain_keys(node, var):
            if (key, line) not in seen:
                seen.add((key, line))
                reads.append((key, line))
        if (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.left, ast.Constant)
                and isinstance(node.left.value, str)
                and isinstance(node.comparators[0], ast.Name)
                and node.comparators[0].id == var):
            mark = (node.left.value, node.lineno)
            if mark not in seen:
                seen.add(mark)
                reads.append(mark)
    return reads


# ---------------------------------------------------------------------------
# native (.cc) extraction


def _cc_function_body(text: str, name: str) -> str | None:
    """Brace-matched body of the first definition of ``name`` — good
    enough for the repo's clang-format style."""
    m = re.search(rf"^[A-Za-z_][\w:<>&*\s]*\b{re.escape(name)}\s*\(",
                  text, re.M)
    if not m:
        return None
    brace = text.find("{", m.end())
    if brace < 0:
        return None
    depth = 0
    for i in range(brace, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[brace:i + 1]
    return None


_CC_OBJECT_KEY = re.compile(r'\{"([A-Za-z0-9_.]+)",')
_CC_SET_KEY = re.compile(r'\.set\("([A-Za-z0-9_.]+)"')
_CC_ROUTE = re.compile(r'path\s*==\s*"(/[^"]*)"')


def cc_producer_keys(text: str, func: str) -> set:
    body = _cc_function_body(text, func)
    if body is None:
        return set()
    return set(_CC_OBJECT_KEY.findall(body)) | set(
        _CC_SET_KEY.findall(body))


def cc_consumer_reads(text: str, func: str, var: str) -> list:
    body = _cc_function_body(text, func)
    if body is None:
        return []
    start = text.find(body)
    base = text.count("\n", 0, start) + 1
    reads = []
    for m in re.finditer(
            rf'\b{re.escape(var)}\s*\.\s*get\(\s*"([A-Za-z0-9_.{{}}="]+)"',
            body):
        reads.append((m.group(1), base + body.count("\n", 0, m.start())))
    return reads


def cc_served_routes(text: str) -> set:
    return set(_CC_ROUTE.findall(text))


# ---------------------------------------------------------------------------
# the metrics key universe (dynamic endpoints)

_HIST_SUFFIXES = ("_count", "_sum", "_p50", "_p99", "_overflow")
_LABELED = re.compile(r'^([a-z0-9_]+)\{([^}]*)\}(_count|_sum|_p50|_p99'
                      r'|_overflow)?$')


def metric_universe(root: Path, files=None) -> tuple:
    """(names, label_keysets): every metric family either side emits,
    plus the label-key sets seen per family — the grammar consumer
    reads of a metrics endpoint are checked against."""
    from . import python_targets
    from .registry import _native_metric_sites, _python_metric_sites

    files = files if files is not None else python_targets(root)
    names: dict = {}
    labels: dict = {}
    for (pattern, is_pattern, kind, _rel, _line, lbls) in (
            _python_metric_sites(files) + _native_metric_sites(root)):
        names.setdefault(pattern, set()).add(
            ("pattern" if is_pattern else "exact", kind))
        labels.setdefault(pattern, set()).add(frozenset(lbls or ()))
    return names, labels


def _match_metric(read: str, names: dict, labels: dict) -> bool:
    """Does a consumer's metric-key read match any emission site? The
    read grammar: family[{k="v",...}][histogram suffix], where the
    family must be emitted and, when labeled, with that label-key
    set."""
    m = _LABELED.match(read)
    if m:
        base = m.group(1)
        label_keys = frozenset(
            p.split("=", 1)[0].strip()
            for p in m.group(2).split(",") if "=" in p)
        return (_family_emitted(base, names, hist=bool(m.group(3)))
                and label_keys in labels.get(base, set()))
    for suf in _HIST_SUFFIXES:
        if read.endswith(suf):
            fam = read[:-len(suf)]
            if _family_emitted(fam, names, hist=True):
                return True
    return _family_emitted(read, names)


def _family_emitted(family: str, names: dict, hist: bool = False) -> bool:
    forms = names.get(family)
    if forms and (not hist or any(kind == "histogram"
                                  for _f, kind in forms)):
        return True
    # f-string emission sites were folded to regexes by the registry
    # scan; a family matches if any pattern fullmatches it.
    for pattern, pforms in names.items():
        for form, kind in pforms:
            if (form == "pattern" and re.fullmatch(pattern, family)
                    and (not hist or kind == "histogram")):
                return True
    return False


# ---------------------------------------------------------------------------
# the pass


def _load_funcs(root: Path, cache: dict, rel: str) -> tuple:
    """(SourceFile, {qualname: FunctionDef}) for a scanned file — the
    SourceFile rides along so inline ``# lint: allow(...)`` comments
    can shield individual consumer-read lines."""
    if rel not in cache:
        path = root / rel
        if not path.exists():
            cache[rel] = (None, None)
        else:
            sf = SourceFile(path, root)
            cache[rel] = (sf, _functions(sf.tree))
    return cache[rel]


def extracted_producer_keys(root: Path, ep, cache: dict,
                            findings: list | None = None) -> set:
    """Union of every producer source's extracted key set."""
    keys: set = set()
    for p in ep.producers:
        if p.file.endswith(".cc"):
            path = root / p.file
            if not path.exists():
                if findings is not None:
                    findings.append(Finding(
                        "endpoint-producer-stale", str(ENDPOINT_CAT_REL),
                        1, f"{ep.server} {ep.path}: producer {p.file} "
                        f"does not exist"))
                continue
            got = cc_producer_keys(path.read_text(), p.func)
        else:
            _sf, funcs = _load_funcs(root, cache, p.file)
            func = funcs.get(p.func) if funcs else None
            if func is None:
                if findings is not None:
                    findings.append(Finding(
                        "endpoint-producer-stale", str(ENDPOINT_CAT_REL),
                        1, f"{ep.server} {ep.path}: producer "
                        f"{p.file}::{p.func} does not exist"))
                continue
            got = producer_keys(func, p.var, p.route)
        keys |= got
    return keys


ENDPOINT_CAT_REL = Path("tools") / "lint" / "endpoint_catalog.py"


def run(root, allowlist, catalog=None, servers=None, files=None) -> list:
    root = Path(root)
    cat = catalog if catalog is not None else ec.CATALOG
    servers = servers if servers is not None else ec.SERVERS
    findings: list = []
    cache: dict = {}
    cat_rel = str(ENDPOINT_CAT_REL)

    # -- 1. route discovery: served routes <-> catalog ----------------------
    by_server: dict = {}
    for ep in cat.values():
        by_server.setdefault(ep.server, set()).update(
            (ep.path, *ep.aliases))
    for server, handlers in servers.items():
        served: set = set()
        for (file, func) in handlers:
            if file.endswith(".cc"):
                path = root / file
                if path.exists():
                    served |= cc_served_routes(path.read_text())
                continue
            _sf, funcs = _load_funcs(root, cache, file)
            f = funcs.get(func) if funcs else None
            if f is None:
                findings.append(Finding(
                    "endpoint-stale", cat_rel, 1,
                    f"server {server}: handler {file}::{func} "
                    f"does not exist"))
                continue
            served |= served_routes(f)
        served = {r for r in served if r.startswith("/")}
        documented = by_server.get(server, set())
        for r in sorted(served - documented):
            findings.append(Finding(
                "endpoint-undocumented", cat_rel, 1,
                f"server {server} serves {r} but endpoint_catalog.py "
                f"has no entry (document it and its key set)"))
        for r in sorted(documented - served):
            findings.append(Finding(
                "endpoint-stale", cat_rel, 1,
                f"catalog documents {server} {r} but no handler "
                f"dispatches on it"))

    # -- 2+3. per-endpoint producer/consumer checks -------------------------
    met_names = met_labels = None
    for ep in cat.values():
        if ep.kind == "prom":
            continue  # Prometheus text: no JSON key contract
        if ep.kind == "metrics":
            if met_names is None:
                met_names, met_labels = metric_universe(root, files)
            for c in ep.consumers:
                _check_consumer_reads(
                    root, ep, c, cache, findings,
                    lambda k: _match_metric(k, met_names, met_labels),
                    "no emission site produces this metric")
            continue
        produced = extracted_producer_keys(root, ep, cache, findings)
        documented = set(ep.keys)
        for k in sorted(produced - documented):
            findings.append(Finding(
                "endpoint-key-undocumented", cat_rel, 1,
                f"{ep.server} {ep.path}: producers emit key "
                f"'{k}' missing from the catalog entry"))
        for k in sorted(documented - produced):
            findings.append(Finding(
                "endpoint-key-stale", cat_rel, 1,
                f"{ep.server} {ep.path}: catalog key '{k}' is emitted "
                f"by no producer (renamed or removed?)"))
        for c in ep.consumers:
            _check_consumer_reads(
                root, ep, c, cache, findings,
                lambda k: k in documented,
                "no producer of this endpoint emits it")

    # -- 4. docs drift -------------------------------------------------------
    if catalog is None:
        doc = root / ENDPOINT_DOC_PATH
        if not doc.exists():
            findings.append(Finding(
                "endpoint-docs-drift", str(ENDPOINT_DOC_PATH), 1,
                "docs/ENDPOINTS.md missing - run python -m tools.lint "
                "--write-endpoint-docs"))
        elif doc.read_text() != ec.render():
            findings.append(Finding(
                "endpoint-docs-drift", str(ENDPOINT_DOC_PATH), 1,
                "docs/ENDPOINTS.md is stale - run python -m tools.lint "
                "--write-endpoint-docs"))

    # Findings on allowlisted endpoints drop here (rule, path) pairs.
    return [f for f in findings
            if not allowed(allowlist, f.rule, f.path, "")]


def _check_consumer_reads(root, ep, c, cache, findings, ok, why):
    cat_rel = str(ENDPOINT_CAT_REL)
    sf = None
    if c.file.endswith(".cc"):
        path = root / c.file
        reads = (cc_consumer_reads(path.read_text(), c.func, c.var)
                 if path.exists() else [])
    else:
        sf, funcs = _load_funcs(root, cache, c.file)
        func = funcs.get(c.func) if funcs else None
        if func is None:
            findings.append(Finding(
                "endpoint-consumer-stale", cat_rel, 1,
                f"{ep.server} {ep.path}: consumer {c.file}::{c.func} "
                f"does not exist"))
            return
        reads = consumer_reads(func, c.var)
    if not reads:
        findings.append(Finding(
            "endpoint-consumer-stale", cat_rel, 1,
            f"{ep.server} {ep.path}: consumer {c.file}::{c.func} "
            f"var '{c.var}' reads nothing (stale entry?)"))
        return
    for key, line in reads:
        if not ok(key):
            if sf is not None and sf.allows(line, "endpoint-ghost-read"):
                continue
            findings.append(Finding(
                "endpoint-ghost-read", c.file, line,
                f"{c.func} reads '{key}' from {ep.server} {ep.path} "
                f"but {why}"))
