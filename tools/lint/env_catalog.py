"""The curated TPUBC_* knob registry.

This dict is the single source of truth the registry-drift pass gates
against: every ``TPUBC_*`` identifier the code reads (Python, C++, CMake,
charts, hack scripts, CI) must have an entry here, every entry here must
still exist in the code, and docs/ENV_VARS.md must be byte-identical to
``render()`` (regenerate with ``python -m tools.lint --write-env-docs``).

Entry: name -> (default, subsystem, description).  Use "-" for
no-default (required / computed) knobs.
"""

CATALOG = {
    # -- control plane / daemons --------------------------------------------
    "TPUBC_LOG": (
        "info", "daemons",
        "Per-target log directives, longest-prefix match "
        "(`info,kube=debug`; `off` silences)."),
    "TPUBC_LOG_FORMAT": (
        "text", "daemons",
        "`json` switches to structured logs carrying trace/span ids."),
    "TPUBC_LOG_RATELIMIT": (
        "1", "daemons",
        "`0` disables the per-(target,message) Warning token bucket."),
    "TPUBC_LOG_RATELIMIT_BURST": (
        "5", "daemons", "Token-bucket burst for repeated Warnings."),
    "TPUBC_LOG_RATELIMIT_SECS": (
        "10", "daemons", "Token-bucket refill interval in seconds."),
    "TPUBC_STATUSZ_RING": (
        "32", "daemons",
        "Per-CR /statusz flight-recorder ring size (1024 objects LRU)."),
    "TPUBC_TRACE_BUFFER": (
        "4096", "telemetry",
        "Span-ring capacity, native and Python tracers alike; `0` "
        "disables request-event recording too."),
    "TPUBC_TRACE_FILE": (
        "-", "telemetry",
        "When set, the span buffer dumps there as Chrome trace JSON at "
        "shutdown/exit."),
    "TPUBC_TRACE_ID": (
        "-", "telemetry",
        "Trace id injected into JobSet workers; workload spans root "
        "under it (admission stamps the CR annotation it rides in on)."),
    # -- slice bootstrap (controller-injected worker env) -------------------
    "TPUBC_COORDINATOR_ADDRESS": (
        "-", "bootstrap",
        "Slice 0 / worker 0's stable address for jax.distributed "
        "initialization (controller-injected)."),
    "TPUBC_JOBSET_NAME": (
        "-", "bootstrap", "Owning JobSet name (controller-injected)."),
    "TPUBC_NUM_HOSTS": (
        "1", "bootstrap", "Hosts per slice (Job parallelism)."),
    "TPUBC_NUM_SLICES": (
        "1", "bootstrap", "Multislice count (absent/1 = one slice)."),
    "TPUBC_SLICE_ID": (
        "0", "bootstrap", "This pod's slice index, from the JobSet."),
    # -- serving data plane -------------------------------------------------
    "TPUBC_KV_BLOCK": (
        "64", "serving", "Paged-pool KV block size in tokens."),
    "TPUBC_PREFILL_BUDGET": (
        "64", "serving",
        "Chunked-prefill token budget per scheduling round."),
    "TPUBC_PREFIX_CACHE": (
        "1", "serving",
        "`0` disables content-hashed KV block sharing (PR 4 refusal "
        "semantics return exactly)."),
    "TPUBC_OVERCOMMIT": (
        "1", "serving",
        "`0` restores whole-footprint refusal admission on the paged "
        "engine (no preemption)."),
    "TPUBC_EXPECTED_NEW": (
        "16", "serving",
        "Seed for the expected-generated-length EMA overcommit "
        "admission reserves by."),
    "TPUBC_SPEC_LOOKUP": (
        "0", "serving",
        "`1` enables n-gram prompt-lookup drafting on the split "
        "draft/verify seam (greedy only)."),
    "TPUBC_INGRESS_MAX_QUEUE": (
        "256", "serving",
        "Waiting-queue bound beyond which /v1/generate answers 429 + "
        "Retry-After."),
    "TPUBC_INGRESS_IDEM_CACHE": (
        "256", "serving",
        "Completed request_id idempotency records retained for replay "
        "(in-flight records never evict; a retry always finds its "
        "stream)."),
    "TPUBC_REQUESTZ_RING": (
        "256", "serving",
        "/requestz flight-recorder ring capacity (retired records "
        "evict first)."),
    "TPUBC_REQUEST_EVENT_CAP": (
        "512", "serving",
        "Per-request lifecycle event cap (overflow counted in "
        "dropped_events)."),
    "TPUBC_REQUEST_EVENTS": (
        "1", "serving",
        "`0` disables request-lifecycle recording entirely (token "
        "streams byte-identical)."),
    "TPUBC_FAULT": (
        "-", "serving",
        "Deterministic fault schedule `site[:prob][:after_n][:seed],...` "
        "(sites: pool.device, alloc, sched.admit, ingress.write, "
        "ckpt.save, scrape, swap.xfer, router.dispatch, router.scrape, "
        "sim.dispatch). Unset = zero-overhead no-op."),
    "TPUBC_DRAIN_TIMEOUT_MS": (
        "5000", "serving",
        "Graceful-drain window: residents finish or checkpoint-preempt "
        "within this before streams flush with `draining: true`."),
    "TPUBC_DEVICE_LEDGER": (
        "1", "serving",
        "`0` disables the per-round busy/idle device-time ledger "
        "(attribution gauges stop; token streams byte-identical)."),
    "TPUBC_HOST_XFER_GBPS": (
        "16", "serving",
        "Host<->device transfer GB/s — seeds the swap-arm cost model "
        "until real transfers feed the measured bandwidth EMA "
        "(`serve_swap_bandwidth_gbps`)."),
    "TPUBC_KV_HOST_BLOCKS": (
        "auto", "serving",
        "Host-DRAM KV tier capacity in blocks: `auto` sizes it at the "
        "HBM pool's own block count, `0` disables the tier (eviction "
        "discards and preemption recomputes — the pre-tier behavior, "
        "byte-identical)."),
    "TPUBC_PROFILEZ": (
        "-", "serving",
        "Enables `POST /profilez` on-demand capture: `1` writes traces "
        "under the system temp dir, any other value is the artifact "
        "dir. Unset/`0` keeps the endpoint 403."),
    "TPUBC_WATCHDOG_STALL_MS": (
        "30000", "serving",
        "Engine-watchdog stall threshold on round heartbeats (/healthz "
        "503 + last_error past it; `0` disables the watchdog)."),
    "TPUBC_ENGINE_MAX_RESTARTS": (
        "8", "serving",
        "Consecutive failed-round recoveries before crash-is-preemption "
        "gives up and the failure propagates (reset on any good round)."),
    "TPUBC_CACHE_DIGEST": (
        "1", "serving",
        "`0` disables prefix-cache digest maintenance (/cachez and "
        "/poolz publish empty digests; token streams byte-identical)."),
    # -- fleet router -------------------------------------------------------
    "TPUBC_ROUTER_SCRAPE_MS": (
        "500", "router",
        "Cadence of the router's own /healthz+/cachez+/poolz scrape "
        "of every replica (breaker-gated; open replicas are probed on "
        "their backoff schedule instead)."),
    "TPUBC_ROUTER_DIGEST_STALE_MS": (
        "3000", "router",
        "Digest freshness window: past it a replica's cache digest "
        "stops being a placement signal and routing degrades to least "
        "queue depth."),
    "TPUBC_ROUTER_BREAKER_MS": (
        "1000", "router",
        "Base backoff of the per-replica circuit breaker (doubles per "
        "consecutive failure, +-20% seeded jitter, capped at 300s — "
        "the PR 9 fleetz schedule)."),
    "TPUBC_ROUTER_HEDGE_MS": (
        "2000", "router",
        "First-token wait before a stalled-heartbeat replica's request "
        "is hedged onto the next-best survivor (`0` disables "
        "hedging)."),
    "TPUBC_ROUTER_RETRIES": (
        "3", "router",
        "Max placement attempts per request before the router gives "
        "an honest 503/terminal failover chunk."),
    # -- digital twin (tools.sim) -------------------------------------------
    "TPUBC_SIM_SLOTS": (
        "8", "sim",
        "Concurrent decode slots per synthetic replica in the fleet "
        "digital twin (`python -m tools.sim`)."),
    "TPUBC_SIM_BLOCK_SIZE": (
        "16", "sim",
        "KV block size (tokens) of the synthetic replicas' two-tier "
        "prefix cache — the unit of the digests the real router "
        "scores."),
    "TPUBC_SIM_DIGEST_BLOCKS": (
        "256", "sim",
        "HBM-tier capacity in blocks per synthetic replica; overflow "
        "parks in a 2x host tier (priced at the swap arm) before "
        "discard."),
    "TPUBC_SIM_MFU_PREFILL": (
        "0.55", "sim",
        "Assumed prefill MFU pricing the twin's per-token prefill time "
        "against `flops_model` / TPUBC_PEAK_TFLOPS (compute-bound "
        "operating point)."),
    "TPUBC_SIM_MFU_DECODE": (
        "0.08", "sim",
        "Assumed decode MFU pricing the twin's per-token decode time "
        "(memory-bound operating point)."),
    # -- telemetry / fleet --------------------------------------------------
    "TPUBC_TS_RING": (
        "256", "telemetry",
        "Per-series time-series ring capacity backing "
        "`/metrics.json?window=N` (deltas/rates/windowed quantiles); "
        "`0` disables history entirely."),
    "TPUBC_FLEET_POLL_MS": (
        "2000", "telemetry",
        "fleetz aggregator scrape cadence per replica (failures back "
        "off exponentially from this, capped at 300s)."),
    # -- kernels / bench ----------------------------------------------------
    "TPUBC_HBM_GBPS": (
        "819", "kernels",
        "HBM peak GB/s — the denominator of every roofline fraction "
        "(v5e default; v5p ~2765, v4 ~1228)."),
    "TPUBC_PEAK_TFLOPS": (
        "197", "kernels",
        "Chip peak bf16 TFLOP/s — the MFU denominator shared by the "
        "serving ledger and the train loop (v5e default; v5p ~459, "
        "v4 ~275)."),
    "TPUBC_QUANT_AUTOTUNE": (
        "1", "kernels",
        "`0` disables the first-call-per-shape block autotuner "
        "(defaults used)."),
    "TPUBC_QUANT_BLOCKS": (
        "-", "kernels",
        "`N,K` pin for the quantized-matmul block sizes (bypasses the "
        "autotuner)."),
    "TPUBC_REPO": (
        "-", "bench",
        "Repo root handed to the bench workload subprocess for "
        "sys.path."),
    "TPUBC_WORKLOAD_TIMEOUT": (
        "1700", "bench",
        "Hard cap in seconds on the workload bench subprocess."),
    "TPUBC_WORKLOAD_INIT_TIMEOUT": (
        "420", "bench",
        "Zero-output backend-init window before a bench attempt is "
        "declared a dead tunnel."),
    # -- native build -------------------------------------------------------
    "TPUBC_SANITIZE": (
        "-", "build",
        "Sanitizer preset for the native build: `address,undefined` or "
        "`thread` (CMake -DTPUBC_SANITIZE=... or env for the g++ "
        "fallback build)."),
    "TPUBC_LIBSSL": (
        "-", "build",
        "CMake variable (not env): the libssl/libcrypto runtime link "
        "line selected for the image."),
    # -- e2e harness --------------------------------------------------------
    "TPUBC_E2E_API_URL": (
        "-", "e2e",
        "Real API-server URL for tests/test_e2e_real_apiserver.py "
        "(unset = skip)."),
    "TPUBC_E2E_TOKEN": (
        "-", "e2e", "Bearer token for the e2e API server."),
    "TPUBC_E2E_CA_FILE": (
        "-", "e2e", "CA bundle for the e2e API server (optional)."),
    "TPUBC_E2E_CLUSTER": (
        "tpubc-e2e", "e2e", "kind cluster name hack/e2e-kind.sh uses."),
    "TPUBC_E2E_HOST_IP": (
        "-", "e2e",
        "Host IP the kind nodes can reach the webhook on (computed by "
        "hack/e2e-kind.sh)."),
    "TPUBC_E2E_KEEP": (
        "0", "e2e",
        "`1` keeps the kind cluster alive after hack/e2e-kind.sh."),
    "TPUBC_CHAOS_ARTIFACT": (
        "-", "e2e",
        "Path the pinned chaos tests dump their /requestz + stream "
        "timeline JSON to (CI uploads it on failure)."),
}

_HEADER = """\
# TPUBC_* knob reference

GENERATED by `python -m tools.lint --write-env-docs` from
tools/lint/env_catalog.py — edit the catalog, not this file.  The
registry-drift lint pass fails when this table and the knobs the code
actually reads diverge.

| Knob | Default | Subsystem | Description |
|---|---|---|---|
"""


def render() -> str:
    rows = []
    for name in sorted(CATALOG):
        default, subsystem, desc = CATALOG[name]
        rows.append(f"| `{name}` | `{default}` | {subsystem} | {desc} |")
    return _HEADER + "\n".join(rows) + "\n"
