"""CLI: ``python -m tools.lint`` — run every pass, print findings,
exit nonzero when any survive the allowlist.

Options:
    --only locks|hotpath|registry|contracts   run one pass family
    --json                           machine-readable findings
    --write-env-docs                 regenerate docs/ENV_VARS.md from
                                     tools/lint/env_catalog.py and exit
    --write-endpoint-docs            regenerate docs/ENDPOINTS.md from
                                     tools/lint/endpoint_catalog.py and
                                     exit
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import DEFAULT_PASSES, REPO_ROOT, run_all
from .env_catalog import render
from .registry import ENV_DOC_PATH


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.lint")
    ap.add_argument("--only", choices=DEFAULT_PASSES,
                    action="append",
                    help="run only the named pass family (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--root", default=None,
                    help="tree to scan (default: this repo)")
    ap.add_argument("--write-env-docs", action="store_true",
                    help="regenerate docs/ENV_VARS.md and exit")
    ap.add_argument("--write-endpoint-docs", action="store_true",
                    help="regenerate docs/ENDPOINTS.md and exit")
    args = ap.parse_args(argv)
    root = Path(args.root) if args.root else REPO_ROOT

    if args.write_env_docs:
        out = root / ENV_DOC_PATH
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(render())
        print(f"wrote {out}")
        return 0

    if args.write_endpoint_docs:
        from .contracts import ENDPOINT_DOC_PATH
        from .endpoint_catalog import render as render_endpoints
        out = root / ENDPOINT_DOC_PATH
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(render_endpoints())
        print(f"wrote {out}")
        return 0

    passes = tuple(args.only) if args.only else DEFAULT_PASSES
    findings = run_all(root, passes)
    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        n = len(findings)
        print(f"tools.lint: {n} finding{'s' if n != 1 else ''} "
              f"({', '.join(passes)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
