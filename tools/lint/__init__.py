"""tpubc-lint: repo-native static analysis (AST-based, stdlib-only).

Three pass families, run over the whole tree by ``python -m tools.lint``
and gated in CI:

* ``locks``    — lock-discipline / race checking driven by the
  ``# guarded-by: <lock>`` annotation convention, plus lock-ordering and
  non-reentrant-reacquire analysis across the scanned classes.
* ``hotpath``  — host-device sync and recompilation hazards inside
  ``@jax.jit``-reachable functions and the serving decode/step/verify
  hot loops.
* ``registry`` — drift between the code and its registries: every
  ``TPUBC_*`` env var documented in docs/ENV_VARS.md, every bench
  ``--check`` key emitted and direction-classified exactly once, every
  metric name consistently typed (counter vs gauge vs histogram).

Deliberate exceptions live in ``tools/lint/allowlist.txt`` (one
``rule path::qualname`` per line) or inline as a trailing
``# lint: allow(rule)`` comment on the offending line.  Seeded-violation
fixtures under ``tools/lint/fixtures/`` prove each pass fires; they are
excluded from the default scan and exercised by tests/test_lint.py.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
ALLOWLIST_PATH = Path(__file__).resolve().parent / "allowlist.txt"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed Python source: AST plus a line -> comment map (the
    annotation convention rides comments, which ast discards)."""

    def __init__(self, path: os.PathLike, root: os.PathLike | None = None):
        self.path = Path(path)
        self.rel = os.path.relpath(self.path, root or REPO_ROOT)
        self.text = self.path.read_text()
        self.tree = ast.parse(self.text, filename=str(self.path))
        self.comments: dict = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def comment_span(self, node: ast.AST) -> str:
        """All comments attached to a (possibly multi-line) statement."""
        end = getattr(node, "end_lineno", node.lineno)
        return " ".join(self.comments.get(ln, "")
                        for ln in range(node.lineno, end + 1)).strip()

    def allows(self, line: int, rule: str) -> bool:
        c = self.comments.get(line, "")
        return f"lint: allow({rule})" in c or "lint: allow-all" in c


def load_allowlist(path: os.PathLike | None = None) -> set:
    """``rule path::qualname`` entries; '#' comments and blanks skipped."""
    p = Path(path or ALLOWLIST_PATH)
    entries = set()
    if not p.exists():
        return entries
    for raw in p.read_text().splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split(None, 1)
        if len(parts) == 2:
            entries.add((parts[0], parts[1].strip()))
    return entries


def allowed(allowlist: set, rule: str, rel: str, qualname: str) -> bool:
    return ((rule, f"{rel}::{qualname}") in allowlist
            or (rule, rel) in allowlist)


def python_targets(root: os.PathLike | None = None) -> list:
    """The default scan set for the AST passes: the workload/runtime
    Python tree plus the bench harness — not tests, not fixtures."""
    root = Path(root or REPO_ROOT)
    files = sorted((root / "tpu_bootstrap").rglob("*.py"))
    files += [root / "bench.py"]
    return [SourceFile(f, root) for f in files
            if "__pycache__" not in f.parts and "fixtures" not in f.parts]


def run_all(root: os.PathLike | None = None,
            passes: tuple = ("locks", "hotpath", "registry")) -> list:
    """Run the requested pass families over the tree; returns findings."""
    from . import hotpath, locks, registry
    root = Path(root or REPO_ROOT)
    allowlist = load_allowlist()
    findings: list = []
    files = python_targets(root)
    if "locks" in passes:
        findings += locks.run(files, allowlist)
    if "hotpath" in passes:
        findings += hotpath.run(files, allowlist)
    if "registry" in passes:
        findings += registry.run(root, allowlist)
    return findings
