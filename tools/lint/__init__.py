"""tpubc-lint: repo-native static analysis (AST-based, stdlib-only).

Four pass families, run over the whole tree by ``python -m tools.lint``
and gated in CI:

* ``locks``    — lock-discipline / race checking driven by the
  ``# guarded-by: <lock>`` annotation convention, plus lock-ordering and
  non-reentrant-reacquire analysis across the scanned classes (including
  the HTTP-handler closures that capture ``outer = self``).
* ``hotpath``  — host-device sync and recompilation hazards inside
  ``@jax.jit``-reachable functions and the serving decode/step/verify
  hot loops.
* ``registry`` — drift between the code and its registries: every
  ``TPUBC_*`` env var documented in docs/ENV_VARS.md, every bench
  ``--check`` key emitted and direction-classified exactly once, every
  metric name consistently typed (counter vs gauge vs histogram) and
  labeled with ONE label-key set per family.
* ``contracts`` — the cross-plane endpoint/JSON contract: every HTTP
  endpoint's statically-extracted produced key set and every consumer's
  key-access paths are gated against the curated catalog
  (tools/lint/endpoint_catalog.py), and docs/ENDPOINTS.md must be
  byte-equal to its rendering.

Deliberate exceptions live in ``tools/lint/allowlist.txt`` (one
``rule path::qualname`` per line) or inline as a trailing
``# lint: allow(rule)`` comment on the offending line.  Every allowlist
entry must still shield a live site: entries no pass consults any more
fail as ``allowlist-stale``.  Seeded-violation fixtures under
``tools/lint/fixtures/`` prove each pass fires; they are excluded from
the default scan and exercised by tests/test_lint.py.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
ALLOWLIST_PATH = Path(__file__).resolve().parent / "allowlist.txt"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed Python source: AST plus a line -> comment map (the
    annotation convention rides comments, which ast discards)."""

    def __init__(self, path: os.PathLike, root: os.PathLike | None = None):
        self.path = Path(path)
        self.rel = os.path.relpath(self.path, root or REPO_ROOT)
        self.text = self.path.read_text()
        self.tree = ast.parse(self.text, filename=str(self.path))
        self.comments: dict = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def comment_span(self, node: ast.AST) -> str:
        """All comments attached to a (possibly multi-line) statement."""
        end = getattr(node, "end_lineno", node.lineno)
        return " ".join(self.comments.get(ln, "")
                        for ln in range(node.lineno, end + 1)).strip()

    def allows(self, line: int, rule: str) -> bool:
        c = self.comments.get(line, "")
        return f"lint: allow({rule})" in c or "lint: allow-all" in c


class Allowlist(set):
    """The allowlist entries plus per-entry source lines and hit
    tracking.  ``allowed()`` marks the entry it matched; after a full
    default run, any entry no lookup ever matched shields nothing and
    fails as ``allowlist-stale`` — the dead-exception gate."""

    def __init__(self, entries=(), lines: dict | None = None):
        super().__init__(entries)
        self.lines: dict = dict(lines or {})
        self.hits: set = set()


def load_allowlist(path: os.PathLike | None = None) -> Allowlist:
    """``rule path::qualname`` entries; '#' comments and blanks skipped."""
    p = Path(path or ALLOWLIST_PATH)
    entries, lines = set(), {}
    if not p.exists():
        return Allowlist()
    for i, raw in enumerate(p.read_text().splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split(None, 1)
        if len(parts) == 2:
            entry = (parts[0], parts[1].strip())
            entries.add(entry)
            lines.setdefault(entry, i)
    return Allowlist(entries, lines)


def allowed(allowlist: set, rule: str, rel: str, qualname: str) -> bool:
    hit = None
    if (rule, f"{rel}::{qualname}") in allowlist:
        hit = (rule, f"{rel}::{qualname}")
    elif (rule, rel) in allowlist:
        hit = (rule, rel)
    if hit is not None and isinstance(allowlist, Allowlist):
        allowlist.hits.add(hit)
    return hit is not None


def python_targets(root: os.PathLike | None = None) -> list:
    """The default scan set for the AST passes: the workload/runtime
    Python tree plus the bench harness and the fleet digital twin
    (tools/sim reads cataloged TPUBC_* knobs and consumes cataloged
    endpoint payloads, so it owes the same honesty) — not tests, not
    fixtures."""
    root = Path(root or REPO_ROOT)
    files = sorted((root / "tpu_bootstrap").rglob("*.py"))
    files += sorted((root / "tools" / "sim").rglob("*.py"))
    files += [root / "bench.py"]
    return [SourceFile(f, root) for f in files
            if "__pycache__" not in f.parts and "fixtures" not in f.parts]


DEFAULT_PASSES = ("locks", "hotpath", "registry", "contracts")


def run_all(root: os.PathLike | None = None,
            passes: tuple = DEFAULT_PASSES) -> list:
    """Run the requested pass families over the tree; returns findings."""
    from . import contracts, hotpath, locks, registry
    root = Path(root or REPO_ROOT)
    allowlist = load_allowlist()
    findings: list = []
    files = python_targets(root)
    if "locks" in passes:
        findings += locks.run(files, allowlist)
    if "hotpath" in passes:
        findings += hotpath.run(files, allowlist)
    if "registry" in passes:
        findings += registry.run(root, allowlist, files)
    if "contracts" in passes:
        findings += contracts.run(root, allowlist, files=files)
    # Dead-entry gate: only sound when every family that can hit an
    # entry actually ran this invocation.
    if set(DEFAULT_PASSES) <= set(passes) and isinstance(allowlist,
                                                         Allowlist):
        for entry in sorted(allowlist - allowlist.hits):
            findings.append(Finding(
                "allowlist-stale", "tools/lint/allowlist.txt",
                allowlist.lines.get(entry, 1),
                f"allowlist entry '{entry[0]} {entry[1]}' shields no "
                f"live site any more — prune it"))
    return findings
