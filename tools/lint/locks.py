"""Lock-discipline / race checker.

Annotation convention
---------------------

A mutable attribute whose every read/write must happen under a lock gets
a trailing comment on its assignment naming that lock::

    self._recs = OrderedDict()   # guarded-by: _lock

``_lock`` must be a ``threading.Lock``/``RLock`` attribute of the same
class; ``threading.Condition(self._lock)`` attributes (and plain
``self._work = self._lock`` aliases) count as acquiring the underlying
lock.  ``# guarded-by: <owner>`` (angle brackets, e.g.
``<engine-thread>``) declares single-thread OWNERSHIP instead: the
annotation is machine-readable documentation — cross-thread access goes
through a published snapshot, not the lock — and the checker records but
does not lock-check those attributes.

Checking
--------

* Every ``self.<attr>`` read or write of a lock-guarded attribute must
  be lexically inside ``with self.<lock>:`` (or an alias) — except in
  ``__init__`` (the object is not shared yet) and in methods whose name
  ends in ``_locked`` (the caller-holds convention).  Call sites of
  ``self.*_locked(...)`` helpers are then themselves checked for holding
  the lock.
* ``lock-reacquire``: calling a method that may acquire a lock the
  caller already holds (``threading.Lock`` is not reentrant — this is a
  self-deadlock, not a race).
* ``lock-order``: nested acquisition order is collected across the whole
  scanned tree (both lexical ``with`` nesting and calls made while a
  lock is held, resolved through inferred attribute/return types); any
  cycle in the resulting order graph is reported.

* Handler closures: an HTTP handler class nested inside a method and
  capturing ``outer = self`` runs its methods on the server's request
  threads — every guarded outer attribute it touches through the alias
  (``outer._draining``) is checked against ``with outer.<lock>:`` just
  like a method body, with the same alias resolution
  (``outer._work`` -> ``Condition(self._lock)`` -> ``_lock``).

Escapes: a trailing ``# lint: allow(lock-guard)`` comment, or an
allowlist entry.  Plain nested functions are still not descended into —
they may execute inline under the caller's locks.
"""

from __future__ import annotations

import ast
import re

from . import Finding, allowed

GUARD_RE = re.compile(r"guarded-by:\s*(<[^>]+>|\w+)")
LOCK_FACTORIES = {"Lock", "RLock"}


def _is_self_attr(node: ast.AST, base: str = "self") -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == base):
        return node.attr
    return None


def _call_name(call: ast.Call) -> str:
    """Dotted name of a call's func, best effort ('' when dynamic)."""
    parts = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class ClassInfo:
    def __init__(self, src, node: ast.ClassDef):
        self.src = src
        self.node = node
        self.name = node.name
        self.guarded: dict = {}      # attr -> lock name or "<owner>"
        self.locks: set = set()      # attrs assigned threading.Lock/RLock
        self.aliases: dict = {}      # attr -> underlying lock attr
        self.methods: dict = {n.name: n for n in node.body
                              if isinstance(n, ast.FunctionDef)}
        self.attr_types: dict = {}   # attr -> set of class names
        self._collect()

    def _collect(self) -> None:
        for meth in self.methods.values():
            for stmt in ast.walk(meth):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                value = stmt.value
                for tgt in targets:
                    attr = _is_self_attr(tgt)
                    if attr is None:
                        continue
                    m = GUARD_RE.search(self.src.comment_span(stmt))
                    if m:
                        self.guarded[attr] = m.group(1)
                    if isinstance(value, ast.Call):
                        callee = _call_name(value)
                        leaf = callee.rsplit(".", 1)[-1]
                        if leaf in LOCK_FACTORIES:
                            self.locks.add(attr)
                        elif leaf == "Condition":
                            arg = value.args[0] if value.args else None
                            under = _is_self_attr(arg) if arg else None
                            if under:
                                self.aliases[attr] = under
                        elif leaf and leaf[0].isupper():
                            self.attr_types.setdefault(attr, set()).add(leaf)
                    other = _is_self_attr(value) if value else None
                    if other and other != attr:
                        # self._work = self._lock style alias
                        self.aliases.setdefault(attr, other)

    def canonical(self, attr: str) -> str:
        seen = set()
        while attr in self.aliases and attr not in seen:
            seen.add(attr)
            attr = self.aliases[attr]
        return attr

    def real_locks(self) -> set:
        """Lock names referenced by guard annotations (non-ownership)."""
        return {self.canonical(g) for g in self.guarded.values()
                if not g.startswith("<")}


def _classes(files) -> dict:
    out: dict = {}
    for src in files:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                out[node.name] = ClassInfo(src, node)
    return out


def _return_types(files) -> dict:
    """Module-level ``def f(...) -> ClassName`` map, keyed by bare name."""
    out: dict = {}
    for src in files:
        for node in src.tree.body:
            if isinstance(node, ast.FunctionDef) and node.returns is not None:
                ret = node.returns
                if isinstance(ret, ast.Name):
                    out[node.name] = ret.id
                elif isinstance(ret, ast.Constant) and isinstance(
                        ret.value, str):
                    out[node.name] = ret.value.strip("'\" ")
    return out


def _receiver_class(call_func: ast.Attribute, cls: ClassInfo,
                    classes: dict, returns: dict,
                    base: str = "self") -> list:
    """Classes a ``<recv>.method(...)`` call may dispatch to."""
    recv = call_func.value
    if isinstance(recv, ast.Name) and recv.id == base:
        return [cls.name]
    attr = _is_self_attr(recv, base)
    if attr is not None:
        return sorted(t for t in cls.attr_types.get(attr, ())
                      if t in classes)
    if isinstance(recv, ast.Call):
        name = _call_name(recv).rsplit(".", 1)[-1]
        t = returns.get(name)
        if t in classes:
            return [t]
        if name in classes:  # direct constructor call
            return [name]
    return []


def _method_calls(meth: ast.FunctionDef):
    for node in ast.walk(meth):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute):
            yield node


def _acquire_summaries(classes: dict, returns: dict) -> dict:
    """(class, method) -> set of (class, lock) the call MAY acquire,
    transitively through resolvable calls (fixed point)."""
    summaries: dict = {}
    for cls in classes.values():
        for mname, meth in cls.methods.items():
            direct = set()
            for node in ast.walk(meth):
                if isinstance(node, ast.With):
                    for item in node.items:
                        attr = _is_self_attr(item.context_expr)
                        if attr and cls.canonical(attr) in cls.locks:
                            direct.add((cls.name, cls.canonical(attr)))
            summaries[(cls.name, mname)] = direct
    changed = True
    while changed:
        changed = False
        for cls in classes.values():
            for mname, meth in cls.methods.items():
                acc = summaries[(cls.name, mname)]
                for call in _method_calls(meth):
                    for tgt in _receiver_class(call.func, cls, classes,
                                               returns):
                        callee = (tgt, call.func.attr)
                        extra = summaries.get(callee, set()) - acc
                        if extra:
                            acc |= extra
                            changed = True
    return summaries


class _MethodChecker(ast.NodeVisitor):
    """Walks one method body tracking the lexically-held lock set."""

    def __init__(self, pass_ctx, cls: ClassInfo, meth: ast.FunctionDef,
                 held: frozenset, base: str = "self",
                 qual: str | None = None):
        self.ctx = pass_ctx
        self.cls = cls
        self.meth = meth
        self.held = set(held)
        self.base = base            # "self", or the closure alias
        self.qual = qual or meth.name

    # Different execution contexts: do not descend.
    def visit_FunctionDef(self, node):
        if node is self.meth:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            attr = _is_self_attr(item.context_expr, self.base)
            if attr is None:
                continue
            lock = self.cls.canonical(attr)
            if lock not in self.cls.locks:
                continue
            me = (self.cls.name, lock)
            if me in self.held:
                self.ctx.finding(
                    "lock-reacquire", self.cls, item.context_expr.lineno,
                    f"{self.cls.name}.{self.qual} re-enters "
                    f"{self.base}.{lock} it already holds "
                    f"(threading.Lock is not reentrant)", self.qual)
            for h in self.held:
                self.ctx.edge(h, me, self.cls, item.context_expr.lineno)
            acquired.append(me)
            self.held.add(me)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for me in acquired:
            self.held.discard(me)

    def visit_Attribute(self, node: ast.Attribute):
        attr = _is_self_attr(node, self.base)
        if attr is not None and attr in self.cls.guarded:
            guard = self.cls.guarded[attr]
            if not guard.startswith("<"):
                lock = self.cls.canonical(guard)
                if (self.cls.name, lock) not in self.held:
                    self.ctx.finding(
                        "lock-guard", self.cls, node.lineno,
                        f"{self.cls.name}.{attr} accessed without "
                        f"holding {self.base}.{guard} "
                        f"(guarded-by: {guard})", self.qual)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute):
            callee_name = node.func.attr
            # _locked-suffix helpers assume the caller holds the lock.
            if (_is_self_attr(node.func, self.base) is not None
                    and callee_name.endswith("_locked")
                    and callee_name in self.cls.methods):
                need = {(self.cls.name, lk)
                        for lk in self.cls.real_locks()}
                if need and not need <= self.held:
                    self.ctx.finding(
                        "lock-helper-unheld", self.cls, node.lineno,
                        f"{self.cls.name}.{callee_name} is a caller-"
                        f"holds helper but {self.qual} calls it "
                        f"without the lock", self.qual)
            if self.held:
                for tgt in _receiver_class(node.func, self.cls,
                                           self.ctx.classes,
                                           self.ctx.returns,
                                           self.base):
                    summary = self.ctx.summaries.get(
                        (tgt, callee_name), set())
                    for lk in summary:
                        if lk in self.held:
                            self.ctx.finding(
                                "lock-reacquire", self.cls, node.lineno,
                                f"{self.cls.name}.{self.qual} holds "
                                f"{lk[0]}.{lk[1]} and calls "
                                f"{tgt}.{callee_name} which may acquire "
                                f"it again (self-deadlock)",
                                self.qual)
                        else:
                            for h in self.held:
                                self.ctx.edge(h, lk, self.cls, node.lineno)
        self.generic_visit(node)


class _PassCtx:
    def __init__(self, classes, returns, summaries, allowlist):
        self.classes = classes
        self.returns = returns
        self.summaries = summaries
        self.allowlist = allowlist
        self.findings: list = []
        self.edges: dict = {}     # (from, to) -> (rel, line)

    def finding(self, rule, cls: ClassInfo, line, msg, qual) -> None:
        rel = cls.src.rel
        if cls.src.allows(line, rule):
            return
        if allowed(self.allowlist, rule, rel, f"{cls.name}.{qual}"):
            return
        self.findings.append(Finding(rule, rel, line, msg))

    def edge(self, frm, to, cls: ClassInfo, line) -> None:
        if frm != to:
            self.edges.setdefault((frm, to), (cls.src.rel, line))


def _find_cycles(edges: dict) -> list:
    """Cycles in the lock-order digraph, reported once each."""
    graph: dict = {}
    for (frm, to) in edges:
        graph.setdefault(frm, set()).add(to)
    cycles, seen_cycles = [], set()

    def dfs(node, stack, onstack):
        for nxt in sorted(graph.get(node, ())):
            if nxt in onstack:
                cyc = tuple(stack[stack.index(nxt):] + [nxt])
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
            elif nxt not in visited:
                visited.add(nxt)
                dfs(nxt, stack + [nxt], onstack | {nxt})

    visited: set = set()
    for start in sorted(graph):
        if start not in visited:
            visited.add(start)
            dfs(start, [start], {start})
    return cycles


def _check_closures(ctx, cls: ClassInfo) -> None:
    """Nested handler classes that capture ``alias = self``: their
    methods run on the HTTP server's request threads, so every guarded
    outer attribute reached through the alias needs the outer lock —
    the checker re-runs per handler method with the alias as base."""
    for mname, meth in cls.methods.items():
        aliases = [
            node.targets[0].id
            for node in ast.walk(meth)
            if isinstance(node, ast.Assign) and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"]
        if not aliases:
            continue
        for node in ast.walk(meth):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in node.body:
                if not isinstance(sub, ast.FunctionDef):
                    continue
                for alias in aliases:
                    qual = (f"{mname}.<locals>.{node.name}.{sub.name}")
                    _MethodChecker(ctx, cls, sub, frozenset(),
                                   base=alias, qual=qual).visit(sub)


def run(files, allowlist: set | None = None) -> list:
    allowlist = allowlist or set()
    classes = _classes(files)
    returns = _return_types(files)
    summaries = _acquire_summaries(classes, returns)
    ctx = _PassCtx(classes, returns, summaries, allowlist)
    for cls in classes.values():
        if not cls.guarded and not cls.locks:
            continue
        locked_names = cls.real_locks()
        for mname, meth in cls.methods.items():
            if mname == "__init__":
                continue
            held = (frozenset((cls.name, lk) for lk in locked_names)
                    if mname.endswith("_locked") else frozenset())
            _MethodChecker(ctx, cls, meth, held).visit(meth)
        _check_closures(ctx, cls)
    for cyc in _find_cycles(ctx.edges):
        pretty = " -> ".join(f"{c}.{lk}" for c, lk in cyc)
        rel, line = ctx.edges.get((cyc[0], cyc[1]), ("", 0))
        ctx.findings.append(Finding(
            "lock-order", rel, line,
            f"inconsistent lock acquisition order (cycle): {pretty}"))
    return ctx.findings
